"""Attention ops: reference softmax attention, a Pallas TPU
flash-attention kernel, and the online-softmax block primitives that
ring attention (singa_tpu/parallel/ring.py) stitches across chips.

The reference system predates transformers — no attention op exists
anywhere in it (layer registry, src/worker/neuralnet.cc:13-33) — so this
is a singa-tpu extension making long-context models first-class. The
kernels follow the standard flash recipe: process K/V blockwise with
running (max, sum, output) statistics per query block so the S x S
score matrix never materializes in HBM.

Each of the three kernels (fwd, dq, dkv) ships in two variants chosen
per call by K/V footprint (_variant): *staged* keeps the whole K/V in
VMEM per program (fastest while it fits), *streamed* keeps K/V in HBM
and double-buffers (D, block) slices through async DMA — VMEM holds
O(block), so sequence length is bounded by HBM, not VMEM (measured
S=131072 single-chip; ~50 TF/s flat across S=8k-131k on v5e, which is
the d=64 MXU roofline — BASELINE.md r4).

All shapes are (batch, heads, seq, head_dim).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """Reference dense attention: softmax(QK^T / sqrt(d)) V."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(mask, scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)


# ---------------------------------------------------------------------
# online-softmax block math (shared by the Pallas kernel and ring
# attention): process one K/V block, fold into running (out, m, l)
# ---------------------------------------------------------------------


def block_attn_update(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    m: jnp.ndarray,
    l: jnp.ndarray,
    *,
    q_offset=0,
    k_offset=0,
    causal: bool = False,
):
    """Fold one K/V block into running flash statistics.

    q (..., Sq, D); k/v (..., Sk, D); out (..., Sq, D) unnormalized;
    m/l (..., Sq) running rowmax / normalizer. Offsets give the global
    positions of the local blocks so causal masking works when the
    sequence is sharded (ring attention) or blocked (the kernel).
    Returns the updated (out, m, l).
    """
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / math.sqrt(d)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[-2])
        kpos = k_offset + jnp.arange(k.shape[-2])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    out = out * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v)
    l = l * alpha + jnp.sum(p, axis=-1)
    return out, m_new, l


def block_attn_init(q_like: jnp.ndarray):
    """Zero-state (out, m, l) for block_attn_update accumulation.

    Derived arithmetically from ``q_like`` (not via zeros()) so that
    under shard_map the state inherits q's varying-axis type and can
    serve as a fori_loop carry (JAX's vma tracking)."""
    out = q_like * 0.0
    m = q_like[..., 0] * 0.0 + NEG_INF
    l = q_like[..., 0] * 0.0
    return out, m, l


def block_attn_finish(out, m, l):
    """Normalize accumulated output (fully-masked rows emit zeros)."""
    safe = jnp.where(l == 0.0, 1.0, l)
    return out / safe[..., None]


# ---------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------

try:  # pallas import kept soft: CPU-only environments use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

if HAS_PALLAS:
    #: jax 0.4.x spells the HBM/unpinned memory space ANY; newer jax,
    #: HBM (or the MemorySpace enum). Chained getattrs never raise, so
    #: an unknown spelling degrades to BlockSpec's default memory space
    #: instead of silently disabling pallas entirely.
    _HBM = (
        getattr(pltpu, "HBM", None)
        or getattr(pltpu, "ANY", None)
        or getattr(getattr(pltpu, "MemorySpace", None), "ANY", None)
    )


def _causal_nlive(q_offset, bq, block_k):
    """Number of K blocks at or below a q block's diagonal — the causal
    loop bound every kernel shares."""
    return jax.lax.div(q_offset + bq - 1, block_k) + 1


def _causal_first(k_offset, block_q):
    """First q block that can see a k block (dkv kernels' loop start)."""
    return jax.lax.div(k_offset, block_q)


def _causal_mask(q_offset, bq, k_offset, bk, transposed=False):
    """(Bq, Bk) keep-mask qpos >= kpos; (Bk, Bq) when ``transposed``."""
    qpos = q_offset + jnp.arange(bq)
    kpos = k_offset + jnp.arange(bk)
    if transposed:
        return qpos[None, :] >= kpos[:, None]
    return qpos[:, None] >= kpos[None, :]


def _stream(hbm, buf, sem, bh_idx):
    """Double-buffered HBM->VMEM block streamer along the LAST axis of
    ``hbm[bh_idx]``.

    Streamed arrays put the sequence on the minor (lane) dimension so
    every block slice is 128-aligned: K/V/Q/dO stream in transposed
    (BH, D, S) layout (D=64 rides the 8-tiled sublanes — slicing the
    64-wide minor dim of an (S, D) layout trips Mosaic's 128-lane tile
    alignment), lse/delta rows in their native (BH, 1, S). ``buf`` is
    (2, rows, block) VMEM scratch — the slot dim must stay a leading
    batch dim (slicing a tiled sublane dim at width 1 is rejected), so
    row vectors buffer as (2, 1, block). ``sem`` is a (2,) DMA
    semaphore array. Returns (start, wait) taking (block_idx, slot).
    """
    block = buf.shape[-1]

    def src(blk):
        return hbm.at[bh_idx, :, pl.ds(blk * block, block)]

    def start(blk, slot):
        pltpu.make_async_copy(src(blk), buf.at[slot], sem.at[slot]).start()

    def wait(blk, slot):
        pltpu.make_async_copy(src(blk), buf.at[slot], sem.at[slot]).wait()

    return start, wait


def _db_loop(lo, hi, streams, compute):
    """Run ``compute(blk, slot, carry)`` over blocks [lo, hi) with all
    ``streams`` ((start, wait) pairs) double-buffered: block i+1's DMA
    is in flight while block i computes."""

    def starts(blk, slot):
        for s, _ in streams:
            s(blk, slot)

    def body(blk, carry):
        slot = jax.lax.rem(blk, 2)

        @pl.when(blk + 1 < hi)
        def _prefetch():
            starts(blk + 1, jax.lax.rem(blk + 1, 2))

        for _, w in streams:
            w(blk, slot)
        return compute(blk, slot, carry)

    starts(lo, jax.lax.rem(lo, 2))
    return lambda carry: jax.lax.fori_loop(lo, hi, body, carry)


def _flash_kernel(
    q_ref, k_hbm, v_hbm, o_ref, lse_ref, kbuf, vbuf, ksem, vsem,
    *, causal, block_k,
):
    """One (batch*head, q-block) program; K/V stream from HBM.

    K^T/V^T live in HBM ((BH, D, S) layout — see _stream) and are
    pulled one (D, block_k) block at a time through double-buffered
    async DMA — VMEM holds O(block), never O(S), so S is bounded by HBM
    capacity, not VMEM (the r3 kernel staged the full K/V per program,
    capping S near 64k). The causal loop bound skips fully-masked K
    blocks entirely — their DMA never starts (a 3-D-grid formulation
    measured ~2x slower here: dead blocks still pay DMA + grid latency).
    The transposed layout also makes every matmul the natural MXU
    orientation: q @ kt for scores, minor-minor contraction for p @ v.
    lse is laid out (BH, 1, S) so every block index is static and
    lane-aligned (Mosaic rejects dynamic sublane loads).
    """
    i = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    seq_k = k_hbm.shape[2]
    nk = seq_k // block_k
    q_offset = qi * bq
    if causal:
        nlive = _causal_nlive(q_offset, bq, block_k)
    else:
        nlive = nk

    q = q_ref[0].astype(jnp.float32)
    scale = 1.0 / math.sqrt(q.shape[-1])
    kst = _stream(k_hbm, kbuf, ksem, i)
    vst = _stream(v_hbm, vbuf, vsem, i)

    def compute(blk, slot, carry):
        out, m, l = carry
        kt = kbuf[slot].astype(jnp.float32)  # (D, Bk)
        vt = vbuf[slot].astype(jnp.float32)
        s = (q @ kt) * scale  # (Bq, Bk)
        if causal:
            mask = _causal_mask(q_offset, bq, blk * block_k, block_k)
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        # p @ v: contract Bk (minor of p) with Bk (minor of vt)
        pv = jax.lax.dot_general(p, vt, (((1,), (1,)), ((), ())))
        out = out * alpha[:, None] + pv
        l = l * alpha + jnp.sum(p, axis=-1)
        return out, m_new, l

    out, m, l = _db_loop(0, nlive, [kst, vst], compute)(block_attn_init(q))
    o_ref[0] = block_attn_finish(out, m, l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(jnp.where(l == 0.0, 1.0, l))


def _flash_bwd_dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_hbm, v_hbm, dq_ref,
    kbuf, vbuf, ksem, vsem, *, causal, block_k, scale,
):
    """dQ for one (batch*head, q-block) program; K^T/V^T stream from HBM.

    FlashAttention backward recurrences: P = exp(S - lse),
    dS = P * (dO V^T - D) with D = rowsum(dO * O), dQ = dS K * scale.
    D arrives precomputed per row (like lse) so neither backward kernel
    redoes the rowsum. Same double-buffered streaming + exact causal
    loop bound as the forward.
    """
    i = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]  # D, (Bq,)
    bq, d = q.shape
    seq_k = k_hbm.shape[2]
    q_offset = qi * bq
    if causal:
        nlive = _causal_nlive(q_offset, bq, block_k)
    else:
        nlive = seq_k // block_k

    kst = _stream(k_hbm, kbuf, ksem, i)
    vst = _stream(v_hbm, vbuf, vsem, i)

    def compute(blk, slot, dq):
        kt = kbuf[slot].astype(jnp.float32)  # (D, Bk)
        vt = vbuf[slot].astype(jnp.float32)
        s = (q @ kt) * scale
        if causal:
            mask = _causal_mask(q_offset, bq, blk * block_k, block_k)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        ds = p * (do @ vt - delta[:, None])
        # ds @ k: contract Bk (minor of ds) with Bk (minor of kt)
        dsk = jax.lax.dot_general(ds, kt, (((1,), (1,)), ((), ())))
        return dq + dsk * scale

    dq = _db_loop(0, nlive, [kst, vst], compute)(
        jnp.zeros((bq, d), dtype=jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, q_hbm, do_hbm, lse_hbm, delta_hbm, dk_ref, dv_ref,
    qbuf, dobuf, lsebuf, dbuf, qsem, dosem, lsesem, dsem,
    *, causal, block_q, scale,
):
    """dK/dV for one (batch*head, k-block) program; Q^T/dO^T/lse/D
    stream from HBM.

    dV = P^T dO; dK = (P * (dO V^T - D))^T Q * scale. With Q/dO
    streaming in transposed (D, Bq) blocks, the kernel works on the
    TRANSPOSED score matrix s_t[kk, qq] directly — k @ qt is the
    natural orientation, and both accumulations contract the shared Bq
    minor dim. The causal loop starts at the first q block that can see
    this k block — earlier blocks' DMA never starts.
    """
    i = pl.program_id(0)
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    seq_q = q_hbm.shape[2]
    nq = seq_q // block_q
    k_offset = ki * bk
    first = _causal_first(k_offset, block_q) if causal else 0

    streams = [
        _stream(q_hbm, qbuf, qsem, i),
        _stream(do_hbm, dobuf, dosem, i),
        _stream(lse_hbm, lsebuf, lsesem, i),
        _stream(delta_hbm, dbuf, dsem, i),
    ]

    def compute(blk, slot, carry):
        dk, dv = carry
        qt = qbuf[slot].astype(jnp.float32)  # (D, Bq)
        dot = dobuf[slot].astype(jnp.float32)
        lse = lsebuf[slot][0]  # (Bq,)
        delta = dbuf[slot][0]
        s_t = (k @ qt) * scale  # (Bk, Bq): transposed scores
        if causal:
            mask = _causal_mask(
                blk * block_q, block_q, k_offset, bk, transposed=True
            )
            s_t = jnp.where(mask, s_t, NEG_INF)
        p_t = jnp.exp(s_t - lse[None, :])  # (Bk, Bq)
        # dO V^T transposed = V dO^T: (Bk, D) @ (D, Bq)
        ds_t = p_t * (v @ dot - delta[None, :])
        # contract Bq (minor of both): dk += ds^T q, dv += p^T do
        dk = dk + jax.lax.dot_general(
            ds_t, qt, (((1,), (1,)), ((), ()))
        ) * scale
        dv = dv + jax.lax.dot_general(p_t, dot, (((1,), (1,)), ((), ())))
        return dk, dv

    zeros = jnp.zeros((bk, d), dtype=jnp.float32)
    dk, dv = _db_loop(first, nq, streams, compute)((zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ------------------- staged-K/V kernel variants -----------------------
# For sequences whose K/V fit a VMEM budget, staging the whole K/V per
# program (grid-pipelined BlockSpec, pl.ds loads) beats HBM streaming:
# measured f+b at S=8192 (v5e, 8 heads, d=64): staged 4.9 ms vs
# streamed 10.4 ms — short live ranges don't amortize per-block DMA.
# Past the budget the streamed kernels take over (S is then bounded by
# HBM, not VMEM): streamed 46-50 TF/s at S=32k-131k where staged
# cannot run at all. Selection in _variant().


def _flash_kernel_staged(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k, seq_k
):
    """One (batch*head, q-block) program; K/V staged whole in VMEM."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    bq, d = q.shape
    nblocks = seq_k // block_k
    q_offset = qi * bq

    def body(i, carry):
        out, m, l = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        return block_attn_update(
            q, k, v, out, m, l,
            q_offset=q_offset, k_offset=i * block_k, causal=causal,
        )

    if causal:
        nlive = _causal_nlive(q_offset, bq, block_k)
    else:
        nlive = nblocks
    out, m, l = jax.lax.fori_loop(
        0, nlive, body,
        (jnp.zeros((bq, d), jnp.float32),
         jnp.full((bq,), NEG_INF, jnp.float32),
         jnp.zeros((bq,), jnp.float32)),
    )
    o_ref[0] = block_attn_finish(out, m, l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(jnp.where(l == 0.0, 1.0, l))


def _flash_bwd_dq_staged(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, causal, block_k, seq_k, scale,
):
    """dQ for one (batch*head, q-block) program; K/V staged in VMEM."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    bq, d = q.shape
    q_offset = qi * bq

    def body(i, dq):
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * scale
        if causal:
            mask = _causal_mask(q_offset, bq, i * block_k, block_k)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        ds = p * (do @ v.T - delta[:, None])
        return dq + (ds @ k) * scale

    if causal:
        nlive = _causal_nlive(q_offset, bq, block_k)
    else:
        nlive = seq_k // block_k
    dq = jax.lax.fori_loop(0, nlive, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_staged(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, causal, block_q, seq_q, scale,
):
    """dK/dV for one (batch*head, k-block) program; Q/dO staged in VMEM."""
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    k_offset = ki * bk

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q)]
        s = (q @ k.T) * scale
        if causal:
            mask = _causal_mask(j * block_q, block_q, k_offset, bk)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        ds = p * (do @ v.T - delta[:, None])
        return dk + (ds.T @ q) * scale, dv + p.T @ do

    nblocks = seq_q // block_q
    first = _causal_first(k_offset, block_q) if causal else 0
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, nblocks, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


#: VMEM staging budget, read ONCE at import: _variant runs at trace
#: time from both the fwd and bwd custom-vjp halves, and jit caches are
#: not keyed on env vars — a mid-process change would leave stale
#: compilations (or mismatched fwd/bwd variants). Fixing it per process
#: keeps variant selection stable.
_FLASH_STAGE_BYTES = (
    float(os.environ.get("SINGA_TPU_FLASH_STAGE_MB", "8")) * 1e6
)


def _variant(s: int, d: int, dtype) -> str:
    """'staged' while K+V for one head row fit the VMEM budget
    (SINGA_TPU_FLASH_STAGE_MB, import-time), else 'streamed'."""
    kv_bytes = 2 * s * d * jnp.dtype(dtype).itemsize
    return "staged" if kv_bytes <= _FLASH_STAGE_BYTES else "streamed"


def _auto_block(s: int) -> int:
    """Largest supported block size dividing S. Measured on TPU v5e
    (S=8192, fwd+bwd): 512-blocks run 4.4x faster than 128-blocks —
    fewer grid programs, longer MXU-resident loops; VMEM per program
    stays small (a 512 x 64 fp32 tile is 128 KB)."""
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    return 128  # _use_kernel rejects non-128-divisible S anyway


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal=False, block_q=None, block_k=None, interpret=None
):
    """Flash attention: Pallas forward AND backward.

    Falls back to the dense reference when Pallas is unavailable, the
    sequence does not tile evenly, or Sq != Sk. ``interpret=True`` runs
    the kernels in the Pallas interpreter (CPU testing); default
    auto-detects TPU. Block sizes default to _auto_block(S); pass
    explicit values to override.

    Training memory is O(S) per head row (out + lse residuals) instead
    of the dense O(S^2): the backward recomputes P blockwise from
    (q, k, v, lse) inside its own kernels.
    """
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _use_kernel(q, k, block_q, block_k, interpret):
    if not HAS_PALLAS:
        return False
    s = q.shape[2]
    if s != k.shape[2]:  # kernel assumes Sq == Sk; dense handles the rest
        return False
    if s % block_q or s % block_k:
        return False
    if not interpret and (block_q % 128 or block_k % 128):
        # on real hardware Mosaic requires lane blocks in multiples of
        # 128: the lse lane dimension is blocked by block_q, and the
        # streamed variant slices the lane (S) dim of the transposed
        # K/V in block_k chunks (the interpreter is laxer — tests
        # exercise smaller geometries there)
        return False
    if interpret is None:
        return jax.default_backend() == "tpu"
    return True


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """-> (out, lse | None); lse None means the dense fallback ran."""
    block_q = block_q or _auto_block(q.shape[2])
    block_k = block_k or _auto_block(k.shape[2])
    if not _use_kernel(q, k, block_q, block_k, interpret):
        return attention(q, k, v, causal=causal), None
    b, h, s, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    qblk = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    lse_blk = pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j))
    out_shape = [
        jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
    ]
    if _variant(s, d, k.dtype) == "staged":
        out, lse = pl.pallas_call(
            functools.partial(
                _flash_kernel_staged,
                causal=causal, block_k=block_k, seq_k=s,
            ),
            grid=(bh, s // block_q),
            in_specs=[
                qblk,
                pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[qblk, lse_blk],
            out_shape=out_shape,
            interpret=bool(interpret),
        )(qf, kf, vf)
        return out.reshape(b, h, s, d), lse
    # streamed: K/V stay in HBM in transposed (BH, D, S) layout (see
    # _stream); the transposes are one XLA pass over K/V, outside the
    # kernel
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, block_k=block_k),
        grid=(bh, s // block_q),
        in_specs=[
            qblk,
            pl.BlockSpec(memory_space=_HBM),  # K^T stays in HBM
            pl.BlockSpec(memory_space=_HBM),  # V^T stays in HBM
        ],
        out_specs=[qblk, lse_blk],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((2, d, block_k), k.dtype),
            pltpu.VMEM((2, d, block_k), v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=bool(interpret),
    )(qf, jnp.swapaxes(kf, 1, 2), jnp.swapaxes(vf, 1, 2))
    return out.reshape(b, h, s, d), lse


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    # resolve auto blocks exactly as the forward did (same S)
    block_q = block_q or _auto_block(q.shape[2])
    block_k = block_k or _auto_block(k.shape[2])
    if lse is None:
        # dense fallback path: recompute through the reference math
        _, vjp = jax.vjp(
            lambda q, k, v: attention(q, k, v, causal=causal), q, k, v
        )
        return vjp(g)
    b, h, s, d = q.shape
    bh = b * h
    scale = 1.0 / math.sqrt(d)
    flat = lambda x: x.reshape(bh, s, d)  # noqa: E731
    # D = rowsum(dO * O), computed ONCE per row and fed to both kernels
    # laid out (BH, 1, S) like lse
    delta = jnp.sum(
        flat(g).astype(jnp.float32) * flat(out).astype(jnp.float32),
        axis=-1,
    )[:, None, :]
    qspec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))
    hbm = pl.BlockSpec(memory_space=_HBM)
    lse_blk = pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j))
    if _variant(s, d, k.dtype) == "staged":
        args = (flat(q), flat(k), flat(v), flat(g), lse, delta)
        full = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
        lse_full = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))
        dq = pl.pallas_call(
            functools.partial(
                _flash_bwd_dq_staged,
                causal=causal, block_k=block_k, seq_k=s, scale=scale,
            ),
            grid=(bh, s // block_q),
            in_specs=[qspec, full, full, qspec, lse_blk, lse_blk],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            interpret=bool(interpret),
        )(*args)
        dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_dkv_staged,
                causal=causal, block_q=block_q, seq_q=s, scale=scale,
            ),
            grid=(bh, s // block_k),
            in_specs=[full, kspec, kspec, full, lse_full, lse_full],
            out_specs=[kspec, kspec],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                jax.ShapeDtypeStruct((bh, s, d), v.dtype),
            ],
            interpret=bool(interpret),
        )(*args)
        unflat = lambda x: x.reshape(b, h, s, d)  # noqa: E731
        return unflat(dq), unflat(dk), unflat(dv)
    kt = jnp.swapaxes(flat(k), 1, 2)  # streamed layouts (see _stream)
    vt = jnp.swapaxes(flat(v), 1, 2)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            causal=causal, block_k=block_k, scale=scale,
        ),
        grid=(bh, s // block_q),
        in_specs=[qspec, qspec, lse_blk, lse_blk, hbm, hbm],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, d, block_k), k.dtype),
            pltpu.VMEM((2, d, block_k), v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=bool(interpret),
    )(flat(q), flat(g), lse, delta, kt, vt)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            causal=causal, block_q=block_q, scale=scale,
        ),
        grid=(bh, s // block_k),
        in_specs=[kspec, kspec, hbm, hbm, hbm, hbm],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, d, block_q), q.dtype),
            pltpu.VMEM((2, d, block_q), g.dtype),
            pltpu.VMEM((2, 1, block_q), jnp.float32),
            pltpu.VMEM((2, 1, block_q), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=bool(interpret),
    )(
        flat(k), flat(v),
        jnp.swapaxes(flat(q), 1, 2), jnp.swapaxes(flat(g), 1, 2),
        lse, delta,
    )
    unflat = lambda x: x.reshape(b, h, s, d)  # noqa: E731
    return unflat(dq), unflat(dk), unflat(dv)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def auto_attention(q, k, v, *, causal=False, n_devices=1):
    """Pick dense vs the Pallas kernel by score-tensor footprint.

    Measured on TPU v5e (BASELINE.md r3): XLA's fused dense attention
    beats the kernel at every size where the S x S score tensor
    comfortably fits HBM, so the kernel's job is the long-context
    regime where dense would blow memory. The footprint estimate is
    per device (fwd+bwd fp32 scores / ``n_devices`` — pass the mesh
    size when batch/seq dims are sharded); the threshold is
    SINGA_TPU_DENSE_ATTN_MB (default 512).
    """
    import os

    b, h, s, _ = q.shape
    scores_mb = b * h * s * s * 4 * 2 / 1e6 / max(1, n_devices)
    if scores_mb <= float(os.environ.get("SINGA_TPU_DENSE_ATTN_MB", "512")):
        return attention(q, k, v, causal=causal)
    return flash_attention(q, k, v, causal)
