"""Scalar activation ops.

Formulas mirror the reference's mshadow scalar op structs
(reference: include/mshadow/cxxnet_op.h:14-113). Gradients are left to jax
autodiff; tests/test_ops.py pins grad(op) against the reference's *_grad
structs (which are written in terms of the *output* for sigmoid/tanh/stanh).
"""

from __future__ import annotations

import jax.numpy as jnp

# LeCun scaled-tanh constants, hard-coded in the reference
# (cxxnet_op.h:77-87). kTanh layers always use these.
STANH_OUTER = 1.7159047
STANH_INNER = 0.66666667


def relu(x: jnp.ndarray, negative_slope: float = 0.0) -> jnp.ndarray:
    """max(x, 0), with optional leaky slope (ReLUProto.negative_slope).

    Plain autodiff. (An output-masked custom VJP — saving y instead of
    the pre-activation for the backward mask — was A/B-measured
    time-neutral on ResNet-50: XLA already shares/fuses the residual.
    r4 perf notes, BASELINE.md.)"""
    # jnp.where (not jnp.maximum) so grad at exactly 0 is 0, matching
    # relu_grad's strict `a > 0 ? 1 : 0` (cxxnet_op.h:31-35)
    return jnp.where(x > 0, x, negative_slope * x if negative_slope else 0.0)


def sigmoid(x: jnp.ndarray) -> jnp.ndarray:
    return 1.0 / (1.0 + jnp.exp(-x))


def stanh(x: jnp.ndarray) -> jnp.ndarray:
    """Scaled tanh: 1.7159047 * tanh(0.66666667 * x)."""
    return STANH_OUTER * jnp.tanh(STANH_INNER * x)


def softplus(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.log1p(jnp.exp(x))


def bnll(x: jnp.ndarray) -> jnp.ndarray:
    """Binomial negative log-likelihood, the overflow-safe softplus
    (cxxnet_op.h:57-61): x > 0 ? x + log(1+exp(-x)) : log(1+exp(x))."""
    return jnp.where(x > 0, x + jnp.log1p(jnp.exp(-jnp.abs(x))),
                     jnp.log1p(jnp.exp(jnp.minimum(x, 0.0))))
