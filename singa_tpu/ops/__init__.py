"""Functional op vocabulary (the reference's L0 math layer, TPU-native).

Everything the reference computes with mshadow expression templates
(include/mshadow/tensor_expr_ext.h, cxxnet_op.h) is expressed here as pure
jnp/lax functions that XLA fuses and tiles onto the MXU/VPU. There is no
backward vocabulary: gradients come from jax autodiff, and the unit tests pin
``jax.grad`` of each forward op to the reference's hand-written *_grad
formulas.
"""

from .activations import (
    bnll,
    relu,
    sigmoid,
    softplus,
    stanh,
    STANH_INNER,
    STANH_OUTER,
)
from .nn import (
    avg_pool2d,
    conv2d,
    dropout,
    lrn,
    max_pool2d,
    pooled_size,
    softmax_loss,
)
from .norm import (
    batch_norm_infer,
    batch_norm_train,
    batch_norm_train_sampled,
)

__all__ = [
    "bnll",
    "relu",
    "sigmoid",
    "softplus",
    "stanh",
    "STANH_INNER",
    "STANH_OUTER",
    "avg_pool2d",
    "conv2d",
    "dropout",
    "lrn",
    "max_pool2d",
    "pooled_size",
    "softmax_loss",
    "batch_norm_infer",
    "batch_norm_train",
    "batch_norm_train_sampled",
]
