"""Train-time image distortion: elastic deformation + random affine.

The reference's MnistImageLayer carries config knobs for the classic
Simard elastic-distortion pipeline — kernel/sigma/alpha (Gaussian-smoothed
random displacement fields), beta (rotation/shear degrees), gamma
(rescale percent), elastic_freq — but ships the implementation commented
out (src/worker/layer.cc:408-440; fields read at :455-463). This module
implements the pipeline for real, as batched JAX ops that run inside the
jitted train step.

Design notes vs the disabled reference code:
- the whole batch distorts in one fused program (vmap over per-sample
  displacement fields + affine matrices) instead of per-record OpenCV
  calls on the prefetch thread;
- the reference halves the shear for labels 1 and 7 (a hand-tuned MNIST
  hack in dead code); that label coupling is not reproduced;
- sampling is bilinear with zero padding outside the canvas, matching
  cv::warpAffine's default border handling closely enough for training.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def gaussian_kernel1d(kernel: int, sigma: float) -> jnp.ndarray:
    """Odd-length normalized Gaussian taps."""
    if kernel % 2 == 0:
        kernel += 1
    x = jnp.arange(kernel, dtype=jnp.float32) - kernel // 2
    k = jnp.exp(-0.5 * (x / max(sigma, 1e-6)) ** 2)
    return k / jnp.sum(k)


def _smooth(field: jnp.ndarray, taps: jnp.ndarray) -> jnp.ndarray:
    """Separable Gaussian blur of a (B,H,W) field (reflect padding)."""
    pad = taps.shape[0] // 2

    def conv1d(x):  # along the last axis
        xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad)), mode="reflect")
        return jax.vmap(
            lambda row: jnp.convolve(row, taps, mode="valid"),
        )(xp.reshape(-1, xp.shape[-1])).reshape(x.shape)

    field = conv1d(field)
    field = conv1d(field.swapaxes(-1, -2)).swapaxes(-1, -2)
    return field


def elastic_offsets(
    rng: jax.Array, shape: tuple[int, int, int], kernel: int, sigma: float,
    alpha: float,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel (dy, dx) displacement fields: uniform noise in [-1,1]
    blurred by a (kernel, sigma) Gaussian and scaled by alpha — Simard's
    elastic distortion, the op the reference's kernel_/sigma_/alpha_
    fields configure."""
    taps = gaussian_kernel1d(kernel, sigma)
    ky, kx = jax.random.split(rng)
    dy = _smooth(jax.random.uniform(ky, shape, minval=-1.0, maxval=1.0), taps)
    dx = _smooth(jax.random.uniform(kx, shape, minval=-1.0, maxval=1.0), taps)
    return dy * alpha, dx * alpha


def affine_matrices(
    rng: jax.Array, n: int, beta: float, gamma: float
) -> jnp.ndarray:
    """(n,2,2) random affine maps: rescale both axes by ±gamma percent,
    then either rotate by ±beta degrees or shear by ±beta/90 (coin flip
    per sample) — the reference's gamma_/beta_ semantics."""
    r = jax.random.uniform(rng, (n, 4), minval=-1.0, maxval=1.0)
    coin = jax.random.bernoulli(jax.random.fold_in(rng, 1), 0.5, (n,))
    sy = 1.0 + r[:, 0] * gamma / 100.0
    sx = 1.0 + r[:, 1] * gamma / 100.0
    theta = r[:, 2] * beta * math.pi / 180.0
    cos, sin = jnp.cos(theta), jnp.sin(theta)
    rot = jnp.stack(
        [jnp.stack([cos, -sin], -1), jnp.stack([sin, cos], -1)], -2
    )
    shear = r[:, 3] * beta / 90.0
    ones, zeros = jnp.ones_like(shear), jnp.zeros_like(shear)
    shr = jnp.stack(
        [jnp.stack([ones, shear], -1), jnp.stack([zeros, ones], -1)], -2
    )
    warp = jnp.where(coin[:, None, None], rot, shr)
    scale = jnp.stack(
        [jnp.stack([sy, zeros], -1), jnp.stack([zeros, sx], -1)], -2
    )
    return warp @ scale


def distort(
    images: jnp.ndarray,
    rng: jax.Array,
    *,
    kernel: int = 0,
    sigma: float = 0.0,
    alpha: float = 0.0,
    beta: float = 0.0,
    gamma: float = 0.0,
) -> jnp.ndarray:
    """Apply elastic + affine distortion to a (B,H,W) image batch.

    Coordinates warp around the image center; sampling is bilinear with
    zero fill. Knobs at zero disable their stage, so any subset of
    {elastic, rotation/shear, rescale} composes.
    """
    b, h, w = images.shape
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32),
        jnp.arange(w, dtype=jnp.float32),
        indexing="ij",
    )
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    ry, rx = jax.random.split(jax.random.fold_in(rng, 17))

    if beta or gamma:
        mats = affine_matrices(ry, b, beta, gamma)
        rel = jnp.stack([yy - cy, xx - cx])  # (2,H,W)
        src = jnp.einsum("nij,jhw->nihw", mats, rel)
        sy = src[:, 0] + cy
        sx = src[:, 1] + cx
    else:
        sy = jnp.broadcast_to(yy, (b, h, w))
        sx = jnp.broadcast_to(xx, (b, h, w))

    if alpha and kernel:
        dy, dx = elastic_offsets(rx, (b, h, w), kernel, sigma, alpha)
        sy = sy + dy
        sx = sx + dx

    def sample(img, y, x):
        return jax.scipy.ndimage.map_coordinates(
            img, [y, x], order=1, mode="constant", cval=0.0
        )

    return jax.vmap(sample)(images, sy, sx)
