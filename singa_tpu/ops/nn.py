"""NN ops: convolution, pooling, LRN, dropout, softmax loss.

These replace the reference's mshadow DNN vocabulary
(include/mshadow/tensor_expr_ext.h:354-577) with XLA-native lowerings:
im2col+gemm becomes ``lax.conv_general_dilated`` (tiled straight onto the
MXU), pool/unpool become ``lax.reduce_window`` + autodiff, chpool becomes
shifted adds over the channel axis (fusable where a channel-axis
reduce_window forced layout shuffles — see lrn()). All arrays are NCHW to
match the reference's layout contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    stride: int = 1,
    pad: int = 0,
    precision=None,
) -> jnp.ndarray:
    """2-D convolution over NCHW input.

    Matches ConvolutionLayer::ComputeFeature (reference:
    src/worker/layer.cc:63-83): out = weight @ im2col(pad(x)) + bias, where
    ``weight`` may be given either as (F, C*k*k) — the reference's col-matrix
    layout — or as (F, C, k, k). mshadow's unpack_patch2col row ordering is
    (c, kh, kw) row-major, so the reshape is exactly OIHW.

    ``precision=None`` resolves by weight dtype: HIGHEST for fp32 (the
    reference accumulates in fp32, cblas_sgemm) and DEFAULT for bf16
    weights (compute_dtype's single-pass MXU mode — HIGHEST would
    multi-pass bf16 back to fp32 cost). An explicit precision always
    wins.
    """
    if weight.ndim == 2:
        nf = weight.shape[0]
        c = x.shape[1]
        k = int(round((weight.shape[1] // c) ** 0.5))
        weight = weight.reshape(nf, c, k, k)
    # mixed precision engages here: under compute_dtype the weights are
    # bf16 while parser-produced activations are fp32 — align to the
    # weight dtype so the MXU sees a true bf16 conv
    x = x.astype(weight.dtype)
    if precision is None:
        precision = (
            lax.Precision.DEFAULT
            if weight.dtype == jnp.bfloat16
            else lax.Precision.HIGHEST
        )
    if _s2d_profitable(x, weight, stride, pad):
        out = _conv2d_space_to_depth(x, weight, stride, pad, precision)
    else:
        out = lax.conv_general_dilated(
            x,
            weight,
            window_strides=(stride, stride),
            padding=[(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            precision=precision,
        )
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _s2d_profitable(x, weight, stride, pad) -> bool:
    """Strided convs over tiny channel counts (an image-stem conv like
    ResNet's 7x7/2 RGB) starve the MXU: C_in=3 means 3-deep dot products
    on a 128-lane array (measured 28 TF/s vs ~190 for mid-net convs on
    v5e). Rewriting via space-to-depth multiplies C_in by stride^2.
    Only the exact-tiling case is rewritten; anything else takes the
    direct path."""
    _, c, h, w = x.shape
    k = weight.shape[2]
    return (
        stride > 1
        and weight.shape[2] == weight.shape[3]  # rewrite assumes square
        and c * k * k <= 256  # only stem-like convs benefit
        and k > stride
        and (h + 2 * pad) % stride == 0
        and (w + 2 * pad) % stride == 0
    )


def _conv2d_space_to_depth(x, weight, stride, pad, precision):
    """y = conv(x, w, stride s, pad p) rewritten as a stride-1 VALID conv
    on the space-to-depth transform of the padded input.

    With a = s*a1 + a2, b = s*b1 + b2 (kernel index split by the stride)
    and z[(c,a2,b2), i, j] = xp[c, s*i + a2, s*j + b2] (xp = padded x):

      y[o,i,j] = sum_{(c,a2,b2),a1,b1} W2[o,(c,a2,b2),a1,b1] z[...,i+a1,j+b1]

    where W2[o,(c,a2,b2),a1,b1] = w[o,c,s*a1+a2,s*b1+b2], zero-padded
    where s*a1+a2 >= k. Exact — same math, MXU-shaped (the parity test
    pins it against the direct lowering)."""
    b, c, h, w = x.shape
    f, _, k, _ = weight.shape
    s = stride
    k2 = -(k // -s)  # ceil(k/s)
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    hs, ws = (h + 2 * pad) // s, (w + 2 * pad) // s
    # (B, C, hs, s, ws, s) -> (B, C, s, s, hs, ws) -> (B, C*s*s, hs, ws)
    z = (
        xp.reshape(b, c, hs, s, ws, s)
        .transpose(0, 1, 3, 5, 2, 4)
        .reshape(b, c * s * s, hs, ws)
    )
    wp = jnp.pad(weight, ((0, 0), (0, 0), (0, k2 * s - k), (0, k2 * s - k)))
    # (F, C, k2, s, k2, s) -> (F, C, s, s, k2, k2) -> (F, C*s*s, k2, k2)
    w2 = (
        wp.reshape(f, c, k2, s, k2, s)
        .transpose(0, 1, 3, 5, 2, 4)
        .reshape(f, c * s * s, k2, k2)
    )
    return lax.conv_general_dilated(
        z,
        w2,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision,
    )


def pooled_size(size: int, kernel: int, stride: int) -> int:
    """Reference pooling output size — ceil mode, window may overhang
    (src/worker/layer.cc:496-500): ceil((size - kernel)/stride) + 1."""
    return -((size - kernel) // -stride) + 1


def _pool(x: jnp.ndarray, kernel: int, stride: int, init, op):
    # Pad bottom/right so the ceil-mode window arithmetic becomes VALID.
    b, c, h, w = x.shape
    ph = (pooled_size(h, kernel, stride) - 1) * stride + kernel
    pw = (pooled_size(w, kernel, stride) - 1) * stride + kernel
    return lax.reduce_window(
        x,
        init,
        op,
        window_dimensions=(1, 1, kernel, kernel),
        window_strides=(1, 1, stride, stride),
        padding=[(0, 0), (0, 0), (0, ph - h), (0, pw - w)],
    )


#: above this many input elements, the phase-decomposed pool backwards
#: lose to autodiff's select_and_scatter / reduce_window (their extra
#: full-array passes dominate once tensors are HBM-bound: ResNet-50's
#: (128, 64, 112, 112) pool1 measured 49.5 vs 47.5 ms/step) — while far
#: below it they win big (AlexNet's small pools: 440 vs 506 us/step).
_PHASE_POOL_MAX_ELEMS = int(32e6)


def max_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """pool<red::maximum> (reference: layer.cc:514-516).

    Small tensors take _max_pool2d_phase — a custom VJP whose backward
    gives dy to EVERY input position equal to its window's max, exactly
    mshadow's unpool semantics (tensor_expr_ext.h:482: `s == maxval`
    ties all share the gradient) and much faster than autodiff's
    select_and_scatter at these sizes. Large tensors keep the autodiff
    path (faster there — see _PHASE_POOL_MAX_ELEMS), whose tie-breaking
    picks a single winner; ties are measure-zero for continuous
    activations, so the semantic difference is confined to exact-equal
    values on the large path."""
    if x.size <= _PHASE_POOL_MAX_ELEMS:
        return _max_pool2d_phase(x, kernel, stride)
    return _pool(x, kernel, stride, -jnp.inf, lax.max)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _max_pool2d_phase(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    return _pool(x, kernel, stride, -jnp.inf, lax.max)


def _max_pool_fwd(x, kernel, stride):
    y = _max_pool2d_phase(x, kernel, stride)
    return y, (x, y)


def _max_pool_bwd(kernel, stride, res, dy):
    """Phase-decomposed unpool: scatter-free (TPU scatters serialize —
    a strided .at[].add formulation measured 1.7x slower than even
    select_and_scatter). Input positions split into stride^2 phase
    grids; each phase's contributing window offsets are static, so
    everything is static slices, compares, adds, and one final
    interleave reshape."""
    x, y = res
    b, c, h, w = x.shape
    s = stride
    ph, pw = y.shape[2], y.shape[3]
    nt, tmax, hp, wp, nq1, nq2 = _phase_grids(kernel, stride, ph, pw)
    # pad x so every phase grid is full; -inf never equals a window max
    xp = jnp.pad(
        x, ((0, 0), (0, 0), (0, hp - h), (0, wp - w)),
        constant_values=-jnp.inf,
    )
    # pad y with +inf (never matches) and dy with 0 so window indices
    # q - t land in-bounds on both edges
    pad_y = ((0, 0), (0, 0), (tmax, nq1 + tmax - ph), (tmax, nq2 + tmax - pw))
    yp = jnp.pad(y, pad_y, constant_values=jnp.inf)
    dyp = jnp.pad(dy, pad_y)

    def win(arr, t1, t2):
        return arr[
            :, :, tmax - t1 : tmax - t1 + nq1, tmax - t2 : tmax - t2 + nq2
        ]

    rows = []
    for r1 in range(s):
        cols = []
        for r2 in range(s):
            xph = xp[:, :, r1::s, r2::s]
            acc = jnp.zeros((b, c, nq1, nq2), dy.dtype)
            for t1 in range(nt[r1]):
                for t2 in range(nt[r2]):
                    acc = acc + win(dyp, t1, t2) * (xph == win(yp, t1, t2))
            cols.append(acc)
        rows.append(cols)
    return (_interleave_phases(rows, b, c, hp, wp, h, w),)


_max_pool2d_phase.defvjp(_max_pool_fwd, _max_pool_bwd)


def _phase_grids(kernel: int, stride: int, ph: int, pw: int):
    """Shared phase-decomposition geometry for the pool backwards:
    -> (nt per residue, tmax, padded input hw, phase grid hw)."""
    s = stride
    nt = [-(-(kernel - r) // s) for r in range(s)]
    tmax = max(nt) - 1
    hp = -(-((ph - 1) * s + kernel) // s) * s
    wp = -(-((pw - 1) * s + kernel) // s) * s
    return nt, tmax, hp, wp, hp // s, wp // s


def _interleave_phases(rows, b, c, hp, wp, h, w):
    """(r1, r2)-indexed phase grids -> (B, C, h, w)."""
    phases = jnp.stack([jnp.stack(cols) for cols in rows])
    dxp = phases.transpose(2, 3, 4, 0, 5, 1).reshape(b, c, hp, wp)
    return dxp[:, :, :h, :w]


def avg_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """pool<red::sum> * 1/k^2 (reference: layer.cc:517-519 — divides by the
    full kernel area even for overhanging border windows).

    Small tensors take the scatter-free phase-decomposed VJP (same
    machinery as max pool, minus the mask: dx[i] = sum of dy over
    covering windows / k^2); large ones keep autodiff (see
    _PHASE_POOL_MAX_ELEMS). Both are exactly linear — no semantic
    difference here, pure speed."""
    if x.size <= _PHASE_POOL_MAX_ELEMS:
        return _avg_pool2d_phase(x, kernel, stride)
    return _pool(x, kernel, stride, 0.0, lax.add) * (1.0 / (kernel * kernel))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _avg_pool2d_phase(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    return _pool(x, kernel, stride, 0.0, lax.add) * (1.0 / (kernel * kernel))


def _avg_pool_fwd(x, kernel, stride):
    return _avg_pool2d_phase(x, kernel, stride), x.shape


def _avg_pool_bwd(kernel, stride, x_shape, dy):
    b, c, h, w = x_shape
    s = stride
    ph, pw = dy.shape[2], dy.shape[3]
    nt, tmax, hp, wp, nq1, nq2 = _phase_grids(kernel, stride, ph, pw)
    pad = ((0, 0), (0, 0), (tmax, nq1 + tmax - ph), (tmax, nq2 + tmax - pw))
    dyp = jnp.pad(dy, pad)

    def win(t1, t2):
        return dyp[
            :, :, tmax - t1 : tmax - t1 + nq1, tmax - t2 : tmax - t2 + nq2
        ]

    inv = 1.0 / (kernel * kernel)
    rows = []
    for r1 in range(s):
        cols = []
        for r2 in range(s):
            acc = jnp.zeros((b, c, nq1, nq2), dy.dtype)
            for t1 in range(nt[r1]):
                for t2 in range(nt[r2]):
                    acc = acc + win(t1, t2)
            cols.append(acc * inv)
        rows.append(cols)
    return (_interleave_phases(rows, b, c, hp, wp, h, w),)


_avg_pool2d_phase.defvjp(_avg_pool_fwd, _avg_pool_bwd)


def lrn(
    x: jnp.ndarray,
    *,
    local_size: int = 5,
    alpha: float = 1.0,
    beta: float = 0.75,
    knorm: float = 1.0,
) -> jnp.ndarray:
    """Cross-channel local response normalization.

    Matches LRNLayer::ComputeFeature (reference: src/worker/layer.cc:356-365):
    norm = chpool_sum(x^2, local_size) * (alpha/local_size) + knorm;
    out = x * norm^(-beta). The channel window is centered with zero padding
    (mshadow chpool, tensor_expr_ext.h:553).

    Lowering chosen by TPU profiling (the LRN layers were ~40% of the
    AlexNet train step before this): the channel window sum is
    ``local_size`` shifted adds — elementwise, so it fuses into the
    surrounding conv epilogue where a channel-axis reduce_window forced
    layout shuffles — and for the ubiquitous beta=0.75 the power lowers
    to rsqrt+sqrt (norm^-0.75 = r*sqrt(r), r = rsqrt(norm)), whose
    backward is a fusable arithmetic chain instead of pow's exp/log.
    """
    salpha = alpha / local_size
    half = local_size // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    c = x.shape[1]
    window_sum = sum(padded[:, i : i + c] for i in range(local_size))
    norm = window_sum * salpha + knorm
    if beta == 0.75:
        r = lax.rsqrt(norm)
        return x * (r * jnp.sqrt(r))
    if beta == 0.5:
        return x * lax.rsqrt(norm)
    return x * jnp.power(norm, -beta)


def dropout(
    rng: jax.Array, x: jnp.ndarray, pdrop: float, training: bool
) -> jnp.ndarray:
    """Inverted-scale Bernoulli dropout.

    Matches DropoutLayer::ComputeFeature (reference: layer.cc:144-155):
    mask = (uniform < pkeep) / pkeep; out = x * mask.
    """
    if not training or pdrop <= 0.0:
        return x
    pkeep = 1.0 - pdrop
    mask = (jax.random.uniform(rng, x.shape) < pkeep).astype(x.dtype) / pkeep
    return x * mask


def softmax_loss(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    topk: int = 1,
    scale: float = 1.0,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """Softmax + cross-entropy + top-k precision in one op.

    Matches SoftmaxLossLayer (reference: src/worker/layer.cc:718-764):
    metric[0] = scale * mean(-log p_true), metric[1] = scale * mean(top-k
    hit). ``jax.grad`` of the returned loss wrt logits is exactly the
    reference's hand-written gradient (prob - onehot) * scale / batchsize.
    """
    labels = labels.astype(jnp.int32)
    # loss math in fp32 even under bf16 compute: softmax/log are where
    # reduced precision actually hurts, and this op is not matmul-bound
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    n = logits.shape[0]
    true_logp = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = -jnp.mean(true_logp) * scale
    _, top_idx = lax.top_k(logits, topk)
    hit = jnp.any(top_idx == labels[:, None], axis=-1)
    precision = jnp.mean(hit.astype(jnp.float32)) * scale
    return loss, {"loss": loss, "precision": precision}
