"""NN ops: convolution, pooling, LRN, dropout, softmax loss.

These replace the reference's mshadow DNN vocabulary
(include/mshadow/tensor_expr_ext.h:354-577) with XLA-native lowerings:
im2col+gemm becomes ``lax.conv_general_dilated`` (tiled straight onto the
MXU), pool/unpool become ``lax.reduce_window`` + autodiff, chpool becomes
shifted adds over the channel axis (fusable where a channel-axis
reduce_window forced layout shuffles — see lrn()). All arrays are NCHW to
match the reference's layout contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    stride: int = 1,
    pad: int = 0,
    precision=None,
) -> jnp.ndarray:
    """2-D convolution over NCHW input.

    Matches ConvolutionLayer::ComputeFeature (reference:
    src/worker/layer.cc:63-83): out = weight @ im2col(pad(x)) + bias, where
    ``weight`` may be given either as (F, C*k*k) — the reference's col-matrix
    layout — or as (F, C, k, k). mshadow's unpack_patch2col row ordering is
    (c, kh, kw) row-major, so the reshape is exactly OIHW.

    ``precision=None`` resolves by weight dtype: HIGHEST for fp32 (the
    reference accumulates in fp32, cblas_sgemm) and DEFAULT for bf16
    weights (compute_dtype's single-pass MXU mode — HIGHEST would
    multi-pass bf16 back to fp32 cost). An explicit precision always
    wins.
    """
    if weight.ndim == 2:
        nf = weight.shape[0]
        c = x.shape[1]
        k = int(round((weight.shape[1] // c) ** 0.5))
        weight = weight.reshape(nf, c, k, k)
    # mixed precision engages here: under compute_dtype the weights are
    # bf16 while parser-produced activations are fp32 — align to the
    # weight dtype so the MXU sees a true bf16 conv
    x = x.astype(weight.dtype)
    if precision is None:
        precision = (
            lax.Precision.DEFAULT
            if weight.dtype == jnp.bfloat16
            else lax.Precision.HIGHEST
        )
    if _s2d_profitable(x, weight, stride, pad):
        out = _conv2d_space_to_depth(x, weight, stride, pad, precision)
    else:
        out = lax.conv_general_dilated(
            x,
            weight,
            window_strides=(stride, stride),
            padding=[(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            precision=precision,
        )
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _s2d_profitable(x, weight, stride, pad) -> bool:
    """Strided convs over tiny channel counts (an image-stem conv like
    ResNet's 7x7/2 RGB) starve the MXU: C_in=3 means 3-deep dot products
    on a 128-lane array (measured 28 TF/s vs ~190 for mid-net convs on
    v5e). Rewriting via space-to-depth multiplies C_in by stride^2.
    Only the exact-tiling case is rewritten; anything else takes the
    direct path."""
    _, c, h, w = x.shape
    k = weight.shape[2]
    return (
        stride > 1
        and weight.shape[2] == weight.shape[3]  # rewrite assumes square
        and c * k * k <= 256  # only stem-like convs benefit
        and k > stride
        and (h + 2 * pad) % stride == 0
        and (w + 2 * pad) % stride == 0
    )


def _conv2d_space_to_depth(x, weight, stride, pad, precision):
    """y = conv(x, w, stride s, pad p) rewritten as a stride-1 VALID conv
    on the space-to-depth transform of the padded input.

    With a = s*a1 + a2, b = s*b1 + b2 (kernel index split by the stride)
    and z[(c,a2,b2), i, j] = xp[c, s*i + a2, s*j + b2] (xp = padded x):

      y[o,i,j] = sum_{(c,a2,b2),a1,b1} W2[o,(c,a2,b2),a1,b1] z[...,i+a1,j+b1]

    where W2[o,(c,a2,b2),a1,b1] = w[o,c,s*a1+a2,s*b1+b2], zero-padded
    where s*a1+a2 >= k. Exact — same math, MXU-shaped (the parity test
    pins it against the direct lowering)."""
    b, c, h, w = x.shape
    f, _, k, _ = weight.shape
    s = stride
    k2 = -(k // -s)  # ceil(k/s)
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    hs, ws = (h + 2 * pad) // s, (w + 2 * pad) // s
    # (B, C, hs, s, ws, s) -> (B, C, s, s, hs, ws) -> (B, C*s*s, hs, ws)
    z = (
        xp.reshape(b, c, hs, s, ws, s)
        .transpose(0, 1, 3, 5, 2, 4)
        .reshape(b, c * s * s, hs, ws)
    )
    wp = jnp.pad(weight, ((0, 0), (0, 0), (0, k2 * s - k), (0, k2 * s - k)))
    # (F, C, k2, s, k2, s) -> (F, C, s, s, k2, k2) -> (F, C*s*s, k2, k2)
    w2 = (
        wp.reshape(f, c, k2, s, k2, s)
        .transpose(0, 1, 3, 5, 2, 4)
        .reshape(f, c * s * s, k2, k2)
    )
    return lax.conv_general_dilated(
        z,
        w2,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision,
    )


def pooled_size(size: int, kernel: int, stride: int) -> int:
    """Reference pooling output size — ceil mode, window may overhang
    (src/worker/layer.cc:496-500): ceil((size - kernel)/stride) + 1."""
    return -((size - kernel) // -stride) + 1


def _pool(x: jnp.ndarray, kernel: int, stride: int, init, op):
    # Pad bottom/right so the ceil-mode window arithmetic becomes VALID.
    b, c, h, w = x.shape
    ph = (pooled_size(h, kernel, stride) - 1) * stride + kernel
    pw = (pooled_size(w, kernel, stride) - 1) * stride + kernel
    return lax.reduce_window(
        x,
        init,
        op,
        window_dimensions=(1, 1, kernel, kernel),
        window_strides=(1, 1, stride, stride),
        padding=[(0, 0), (0, 0), (0, ph - h), (0, pw - w)],
    )


def max_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """pool<red::maximum> (reference: layer.cc:514-516)."""
    return _pool(x, kernel, stride, -jnp.inf, lax.max)


def avg_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """pool<red::sum> * 1/k^2 (reference: layer.cc:517-519 — divides by the
    full kernel area even for overhanging border windows)."""
    return _pool(x, kernel, stride, 0.0, lax.add) * (1.0 / (kernel * kernel))


def lrn(
    x: jnp.ndarray,
    *,
    local_size: int = 5,
    alpha: float = 1.0,
    beta: float = 0.75,
    knorm: float = 1.0,
) -> jnp.ndarray:
    """Cross-channel local response normalization.

    Matches LRNLayer::ComputeFeature (reference: src/worker/layer.cc:356-365):
    norm = chpool_sum(x^2, local_size) * (alpha/local_size) + knorm;
    out = x * norm^(-beta). The channel window is centered with zero padding
    (mshadow chpool, tensor_expr_ext.h:553).

    Lowering chosen by TPU profiling (the LRN layers were ~40% of the
    AlexNet train step before this): the channel window sum is
    ``local_size`` shifted adds — elementwise, so it fuses into the
    surrounding conv epilogue where a channel-axis reduce_window forced
    layout shuffles — and for the ubiquitous beta=0.75 the power lowers
    to rsqrt+sqrt (norm^-0.75 = r*sqrt(r), r = rsqrt(norm)), whose
    backward is a fusable arithmetic chain instead of pow's exp/log.
    """
    salpha = alpha / local_size
    half = local_size // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    c = x.shape[1]
    window_sum = sum(padded[:, i : i + c] for i in range(local_size))
    norm = window_sum * salpha + knorm
    if beta == 0.75:
        r = lax.rsqrt(norm)
        return x * (r * jnp.sqrt(r))
    if beta == 0.5:
        return x * lax.rsqrt(norm)
    return x * jnp.power(norm, -beta)


def dropout(
    rng: jax.Array, x: jnp.ndarray, pdrop: float, training: bool
) -> jnp.ndarray:
    """Inverted-scale Bernoulli dropout.

    Matches DropoutLayer::ComputeFeature (reference: layer.cc:144-155):
    mask = (uniform < pkeep) / pkeep; out = x * mask.
    """
    if not training or pdrop <= 0.0:
        return x
    pkeep = 1.0 - pdrop
    mask = (jax.random.uniform(rng, x.shape) < pkeep).astype(x.dtype) / pkeep
    return x * mask


def softmax_loss(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    topk: int = 1,
    scale: float = 1.0,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """Softmax + cross-entropy + top-k precision in one op.

    Matches SoftmaxLossLayer (reference: src/worker/layer.cc:718-764):
    metric[0] = scale * mean(-log p_true), metric[1] = scale * mean(top-k
    hit). ``jax.grad`` of the returned loss wrt logits is exactly the
    reference's hand-written gradient (prob - onehot) * scale / batchsize.
    """
    labels = labels.astype(jnp.int32)
    # loss math in fp32 even under bf16 compute: softmax/log are where
    # reduced precision actually hurts, and this op is not matmul-bound
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    n = logits.shape[0]
    true_logp = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = -jnp.mean(true_logp) * scale
    _, top_idx = lax.top_k(logits, topk)
    hit = jnp.any(top_idx == labels[:, None], axis=-1)
    precision = jnp.mean(hit.astype(jnp.float32)) * scale
    return loss, {"loss": loss, "precision": precision}
