"""Metric averaging (the reference's Performance class).

Worker::Performance accumulates each loss layer's metric blob every step
and prints the element-wise average every display interval, then resets
(src/worker/worker.cc:350-386). Metrics arrive here as jnp scalars; they
are kept on device and only pulled to host at Avg() time so accumulation
never blocks the async dispatch queue.
"""

from __future__ import annotations

import numpy as np


class Performance:
    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._sums: dict[str, dict[str, object]] = {}
        self._count = 0

    def update(self, metrics: dict[str, dict]) -> None:
        """Accumulate one step's {losslayer: {metric: scalar}}.

        Sums are folded into one running device scalar per metric (a lazy
        device-side add) so memory stays constant over arbitrarily long
        display intervals and no step ever blocks on a host sync.
        """
        self._count += 1
        for lname, m in metrics.items():
            bucket = self._sums.setdefault(lname, {})
            for k, v in m.items():
                bucket[k] = v if k not in bucket else bucket[k] + v

    def update_summed(self, summed: dict[str, dict], nsteps: int) -> None:
        """Accumulate ``nsteps`` steps whose metrics are already summed
        on device (the chunk engine's lax.scan output reduced over its
        step axis) — no per-step host transfer, same averages.

        ``nsteps <= 0`` is a no-op: a zero-length window carries no
        steps, so folding its sums in while netting the count to zero
        would silently skew the next window's averages."""
        if nsteps <= 0:
            return
        self.update(summed)
        self._count += nsteps - 1

    @property
    def count(self) -> int:
        return self._count

    def avg(self) -> dict[str, dict[str, float]]:
        """Element-wise averages since the last reset (worker.cc:367-376).

        All metrics are pulled to host in ONE transfer: `float(total)`
        per metric costs a full device round trip each (~115 ms through
        a tunneled TPU — the r4 flagship-run profile showed 4 of these
        per display window, half the run's wall clock)."""
        n = max(self._count, 1)
        names = [(l, k) for l, b in self._sums.items() for k in b]
        if not names:
            return {}
        import jax.numpy as jnp

        vals = np.asarray(
            jnp.stack(
                [jnp.asarray(self._sums[l][k], jnp.float32) for l, k in names]
            )
        )
        out: dict[str, dict[str, float]] = {}
        for (l, k), v in zip(names, vals):
            out.setdefault(l, {})[k] = float(v) / n
        return out

    def to_string(self, avg: dict | None = None) -> str:
        """One-line display like Worker's "loss : 2.301, precision : 0.11".

        Pass an already-computed ``avg()`` dict to avoid a second device
        round trip (the eval path computes avg for its return value and
        logs in the same breath)."""
        parts = []
        for lname, bucket in sorted((avg or self.avg()).items()):
            inner = ", ".join(f"{k} : {v:.6g}" for k, v in sorted(bucket.items()))
            parts.append(f"{lname} [{inner}]" if len(self._sums) > 1 else inner)
        return ", ".join(parts) if parts else "no metrics"
