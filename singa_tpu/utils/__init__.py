"""Observability utilities: metric averaging, phase timers, graph viz."""

from .metrics import Performance
from .timers import Timers
from .viz import dump_net_json

__all__ = ["Performance", "Timers", "dump_net_json"]
