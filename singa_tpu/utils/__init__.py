"""Observability utilities: metric averaging, phase timers, FLOPs/MFU
accounting, graph viz."""

from .flops import device_peak_flops, net_fwd_flops, train_step_flops
from .metrics import Performance
from .timers import Timers
from .viz import dump_net_json

__all__ = [
    "Performance",
    "Timers",
    "device_peak_flops",
    "dump_net_json",
    "net_fwd_flops",
    "train_step_flops",
]
