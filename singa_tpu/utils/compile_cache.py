"""Persistent XLA compilation cache: warm-start repeat runs.

BENCH_r05 measured 60-135 ms of fixed per-run startup overhead, mostly
XLA recompilation of programs that are bit-identical across runs (the
train step, the eval chunks, the snapshot copy). jax ships a persistent
compilation cache keyed on the lowered computation; pointing it at a
directory makes every run after the first skip those compiles entirely.

Resolution order for the cache directory (first hit wins):

  1. ``SINGA_TPU_COMPILE_CACHE`` env var — operators override per run
     (the values ``0``/``off``/``none`` disable the cache)
  2. ``ClusterConfig.compile_cache_dir`` — the cluster conf pins a
     shared location (same ``off`` spellings disable)
  3. ``<workspace>/compile_cache`` — the default for any job with a
     workspace; jobs without one run uncached (nowhere durable to put it)

``bench.py`` measures the realized warm-start delta (cold vs warm first
step) and reports it as ``compile_warm_start`` in its output.
"""

from __future__ import annotations

import os

_OFF = ("", "0", "off", "none", "false")


def resolve_cache_dir(cluster_cfg=None) -> str | None:
    """The persistent-cache directory the resolution order picks, or
    None when caching is disabled / unconfigured."""
    path = os.environ.get("SINGA_TPU_COMPILE_CACHE")
    if path is None and cluster_cfg is not None:
        if cluster_cfg.compile_cache_dir:
            path = cluster_cfg.compile_cache_dir
        elif cluster_cfg.workspace:
            path = os.path.join(cluster_cfg.workspace, "compile_cache")
    if path is None or path.strip().lower() in _OFF:
        return None
    return path


def enable_compile_cache(path: str, log=print) -> bool:
    """Point jax's persistent compilation cache at ``path``. The
    min-time/min-size gates are zeroed: singa-tpu jobs compile a handful
    of large programs, so every entry is worth keeping. Returns False
    (and keeps running uncached) on jax builds without the knobs."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - version-dependent
        log(f"persistent compile cache unavailable ({e}); running uncached")
        return False
    return True


def disable_compile_cache(log=print) -> None:
    """Turn the persistent cache off for the rest of this process.

    The supervisor calls this before an in-process restart attempt
    rebuilds the trainer: re-jitting the same programs in the process
    that just wrote their cache entries can crash jaxlib's executable
    deserialization (segfault observed on the CPU backend after a
    mid-run crash). Restarts are the rare path — losing the cache there
    costs one recompile; the cross-process warm start (the actual win)
    is untouched."""
    import jax

    try:
        if jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", None)
            log(
                "persistent compile cache: disabled for restart attempts "
                "(in-process re-read of fresh entries is not crash-safe)"
            )
    except Exception:  # pragma: no cover - version-dependent
        pass


def setup_compile_cache(cluster_cfg=None, log=print) -> str | None:
    """Resolve + enable in one call (main.py's entry). Returns the
    active cache dir, or None when disabled."""
    path = resolve_cache_dir(cluster_cfg)
    if path is None:
        return None
    if not enable_compile_cache(path, log=log):
        return None
    log(f"persistent compile cache: {path}")
    return path
