"""Net-structure observability: node-link JSON dumps.

The reference writes a JSON graph per phase into the cluster's
vis_subfolder for script/graph.py to render (NeuralNet::ToString,
src/worker/neuralnet.cc:325-332; Cluster::vis_folder,
include/utils/cluster.h:70-73). Net.to_json produces the same node-link
shape; this writes it where the reference would.
"""

from __future__ import annotations

import json
import os

from ..graph.builder import Net


def dump_net_json(net: Net, folder: str) -> str:
    """Write <folder>/<phase>.json; returns the path."""
    os.makedirs(folder, exist_ok=True)
    path = os.path.join(folder, f"{net.phase}.json")
    with open(path, "w") as f:
        json.dump(net.to_json(), f, indent=2)
    return path
