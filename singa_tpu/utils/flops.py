"""Analytic model-FLOPs accounting for MFU reporting.

The reference has no FLOPs accounting at all — its perf surface is the
per-phase millisecond timers (include/worker/worker.h:91-114). Matching
"fast vs yesterday" is not "fast vs the chip", so bench.py pairs those
timers with an analytic FLOPs walk over the built Net and reports
model-FLOPs utilization (MFU) against the device's peak.

Conventions (the standard MFU accounting, e.g. the PaLM appendix):
only matmul-class FLOPs are counted (convs, dense/inner-product layers,
attention projections and score/value matmuls); elementwise ops,
normalizations, pooling, and softmax are omitted. A multiply-add is 2
FLOPs. The backward pass is 2x the forward (one matmul each for the
input grad and the weight grad), so one train step costs 3x the forward
walk. Causal attention scores count at half density — the flash kernel
(ops/attention.py) really does skip the upper-triangle blocks.
"""

from __future__ import annotations

import math
import os


def layer_fwd_flops(layer, src_shapes: list[tuple]) -> float:
    """Matmul FLOPs of one layer's forward pass for a full batch."""
    t = layer.TYPE
    out = layer.out_shape
    if t == "kConvolution":
        b, f, h, w = out
        # setup() resolved the channel count (3-D sources are implicit
        # single-channel, layers/neuron.py) — don't re-derive from shape
        c = layer.channels
        return 2.0 * b * f * h * w * c * layer.kernel * layer.kernel
    if t in ("kInnerProduct", "kRBM"):
        b = src_shapes[0][0]
        fan_in = math.prod(src_shapes[0][1:])
        return 2.0 * b * fan_in * out[-1]
    if t == "kDense":
        d = src_shapes[0][-1]
        return 2.0 * math.prod(out[:-1]) * d * out[-1]
    if t == "kAttention":
        b, s, d = src_shapes[0]
        proj = 8.0 * b * s * d * d  # qkv (6bsd^2) + out (2bsd^2)
        scores = 4.0 * b * s * s * d  # QK^T + PV
        return proj + scores / 2.0  # causal: half the blocks run
    if t == "kMoE":
        # per token: router (negligible) + ONE routed expert's 2-layer FFN
        b, s, d = src_shapes[0]
        d_ff = getattr(layer, "d_ff", d)
        return 2.0 * b * s * (d * d_ff + d_ff * d)
    return 0.0


def net_fwd_flops(net) -> tuple[float, dict[str, float]]:
    """-> (total forward matmul FLOPs per batch, per-layer breakdown)."""
    per: dict[str, float] = {}
    for layer in net.layers:
        srcs = [net.name2layer[s].out_shape for s in layer.srclayers]
        f = layer_fwd_flops(layer, srcs)
        if f:
            per[layer.name] = f
    return sum(per.values()), per


def train_step_flops(net) -> float:
    """Model FLOPs of one forward+backward train step (3x forward)."""
    total, _ = net_fwd_flops(net)
    return 3.0 * total


def cd_step_flops(net) -> float:
    """Model FLOPs of one greedy-layerwise CD-k train step (CDTrainer).

    The 3x-forward backprop convention does not apply: CD has no
    backward pass. Per RBM, one step runs the positive-phase up-prop
    (2bvh), cd_k Gibbs iterations (down + up, 4bvh each), and the two
    gradient outer products v0^T h0 and vk^T hk (2bvh each) — all
    matmul-class, everything else (sigmoids, Bernoulli draws, bias
    grads) omitted per the MFU convention above. Non-RBM layers in the
    chain (parsers) contribute their forward cost once."""
    total = 0.0
    for layer in net.layers:
        srcs = [net.name2layer[s].out_shape for s in layer.srclayers]
        if layer.TYPE != "kRBM":
            total += layer_fwd_flops(layer, srcs)
            continue
        b = srcs[0][0]
        v = math.prod(srcs[0][1:])
        h = layer.hdim
        bvh = 2.0 * b * v * h
        total += bvh * (1 + 2 * layer.cd_k + 2)
    return total


#: bf16 matmul peak per chip, by device_kind substring (first match wins).
#: Sources: public TPU system specs (cloud.google.com/tpu/docs/system-*).
_PEAKS = (
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v6 lite", 918e12),  # v6e / Trillium
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def device_peak_flops(device=None) -> float | None:
    """bf16 peak FLOP/s of one chip, or None when unknown (e.g. CPU).

    Override with SINGA_TPU_PEAK_TFLOPS for hardware not in the table.
    """
    env = os.environ.get("SINGA_TPU_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAKS:
        if key in kind:
            return peak
    return None
