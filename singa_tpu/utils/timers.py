"""Per-phase wall-clock timers (TimerInfo parity).

The reference's Executor keeps millisecond accumulators per phase —
tForward_/tBackward_/tSyncData_/tSyncParam_ — and prints them with the
metrics each display interval (include/worker/worker.h:91-114). One jitted
XLA program fuses forward/backward/update, so the TPU-native phases are:

  train  — device step time (dispatch..ready, measured at sync points)
  data   — host batch assembly + transfer
  eval   — test/validation passes

Use ``jax.profiler`` traces when per-op attribution is needed; these
counters are the always-on cheap layer, like the reference's.
"""

from __future__ import annotations

import contextlib
import time


class Timers:
    def __init__(self, span_sink=None):
        #: span-recording mode (singa_tpu/obs/): when set, every phase
        #: occurrence ALSO calls ``span_sink(name, t0_wall, dur, steps)``
        #: — the flight recorder buffers it as a Chrome-trace span. The
        #: sink must do no I/O and no device work (obs/recorder.py's
        #: contract); ``reset()`` leaves it attached.
        self.span_sink = span_sink
        self.reset()

    def reset(self) -> None:
        self._acc: dict[str, float] = {}
        self._n: dict[str, int] = {}
        self._steps: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str, steps: int = 1):
        """Time one occurrence of ``name``. ``steps`` is how many train
        steps the occurrence covers (chunked dispatch windows pass the
        window length) — feeds the per-STEP means and the span export;
        accumulators are otherwise unchanged."""
        sink = self.span_sink
        t0w = time.time() if sink is not None else 0.0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._acc[name] = self._acc.get(name, 0.0) + dt
            self._n[name] = self._n.get(name, 0) + 1
            self._steps[name] = self._steps.get(name, 0) + max(1, steps)
            if sink is not None:
                sink(name, t0w, dt, steps)

    def total(self, name: str) -> float:
        return self._acc.get(name, 0.0)

    def phases(self) -> list[str]:
        """Names of every phase that has accumulated time."""
        return sorted(self._acc)

    def mean_ms(self, name: str) -> float:
        n = self._n.get(name, 0)
        return (self._acc.get(name, 0.0) / n * 1000.0) if n else 0.0

    def steps(self, name: str) -> int:
        """Train steps covered by ``name``'s occurrences (chunk windows
        count their whole window — see ``phase(steps=)``)."""
        return self._steps.get(name, 0)

    def share(self, name: str, *others: str) -> float:
        """``name``'s fraction of the time accumulated across ``name`` +
        ``others`` (the display line's input-stall percentage). 0.0 when
        nothing has accumulated."""
        total = sum(self.total(p) for p in (name, *others))
        return self.total(name) / total if total > 0 else 0.0

    def to_string(self) -> str:
        """"train 12.3ms, data 0.8ms" — the TimerInfo display line."""
        return ", ".join(
            f"{k} {self.mean_ms(k):.2f}ms/it" for k in sorted(self._acc)
        ) or "no timing"
