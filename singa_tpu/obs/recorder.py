"""FlightRecorder: the always-on, zero-extra-device-sync event log.

Each rank appends JSONL records to ``<workspace>/events/rank_k.jsonl``.
One record per lifecycle event — run start/stop, display-cadence step
records, checkpoint snapshot/write/commit/LATEST promotion, guard
verdicts, fault firings, preemption drains, heartbeat death verdicts,
supervisor restarts — plus (span mode) one record per timed phase
occurrence, which ``tools/trace.py`` turns into Chrome-trace tracks.

The step-path contract, in order of importance:

  1. ``event()``/``record_span()`` NEVER touch the device and NEVER
     perform I/O: they append a plain dict to an in-memory buffer under
     a lock. Payload values must already be host scalars — the flush's
     ``json.dumps`` runs with no fallback encoder precisely so a device
     array smuggled into a payload fails loudly in tests instead of
     silently syncing at flush time.
  2. ``flush()`` is the only writer, called at display-cadence
     boundaries and at lifecycle edges (drain, restart, stop) — the
     same points that already pay a host sync for the display line.
  3. Everything is thread-safe: the async-ckpt writer thread, the
     feeder/stager threads, and the watchdog thread all record into the
     same buffer.

Records carry BOTH clocks: ``ts`` (wall, ``time.time()``) for
cross-rank merging — ranks share no monotonic epoch — and ``mono``
(``time.perf_counter()``) for exact intra-rank durations.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time


def config_hash(model_cfg) -> str:
    """Deterministic 12-hex digest of a ModelConfig — the run identity
    every rank derives independently (no coordination needed: all ranks
    parse the same config text)."""
    try:
        blob = json.dumps(model_cfg.to_dict(), sort_keys=True, default=str)
    except Exception:
        blob = repr(model_cfg)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


class FlightRecorder:
    """Per-rank buffered JSONL event log + span sink."""

    def __init__(
        self,
        events_dir: str,
        *,
        rank: int = 0,
        run_id: str = "",
        trace_spans: bool = True,
        log=print,
    ):
        self.events_dir = events_dir
        self.path = os.path.join(events_dir, f"rank_{rank}.jsonl")
        self.rank = int(rank)
        self.run_id = run_id
        self.trace_spans = bool(trace_spans)
        self.log = log
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        #: last step a caller stamped (events without an explicit step
        #: inherit it — e.g. the async writer publishing step k's save
        #: while the loop is at k+j)
        self.step: int | None = None
        #: counters tests pin the zero-syscall contract with
        self.recorded = 0
        self.flushes = 0
        self.writes = 0  # file opens — must equal flushes with content

    # ------------------------------------------------------------------
    # recording (no I/O, no device access)
    # ------------------------------------------------------------------

    def event(self, kind: str, step: int | None = None, **payload) -> None:
        """Append one lifecycle event to the buffer. Payload values must
        be host-side JSON scalars/containers (see module docstring)."""
        rec = {
            "ts": time.time(),
            "mono": time.perf_counter(),
            "rank": self.rank,
            "run": self.run_id,
            "step": self.step if step is None else int(step),
            "kind": kind,
        }
        if payload:
            rec["data"] = payload
        with self._lock:
            self._buf.append(rec)
            self.recorded += 1

    def record_span(
        self,
        name: str,
        t0_wall: float,
        dur: float,
        *,
        track: str = "phases",
        steps: int | None = None,
    ) -> None:
        """One completed span (a Chrome-trace 'X' event after merge).
        ``t0_wall`` is the wall-clock start, ``dur`` seconds. No-op when
        span recording is off — the event log stays lifecycle-only."""
        if not self.trace_spans:
            return
        rec = {
            "ts": t0_wall,
            "mono": time.perf_counter(),
            "rank": self.rank,
            "run": self.run_id,
            "step": self.step,
            "kind": "span",
            "name": name,
            "track": track,
            "dur": dur,
        }
        if steps is not None:
            rec["steps"] = int(steps)
        with self._lock:
            self._buf.append(rec)
            self.recorded += 1

    @contextlib.contextmanager
    def span(self, name: str, *, track: str = "phases",
             steps: int | None = None):
        """Context-manager form of ``record_span`` (feeder/stager/writer
        threads wrap their unit of work in one)."""
        if not self.trace_spans:
            yield
            return
        t0w = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_span(
                name, t0w, time.perf_counter() - t0,
                track=track, steps=steps,
            )

    def phase_span(
        self, name: str, t0_wall: float, dur: float, steps: int | None = None
    ) -> None:
        """The ``Timers`` span-sink signature (utils/timers.py): every
        timed phase occurrence becomes a span on the 'phases' track."""
        self.record_span(name, t0_wall, dur, track="phases", steps=steps)

    # ------------------------------------------------------------------
    # flushing (the only I/O)
    # ------------------------------------------------------------------

    def flush(self) -> None:
        """Append the buffered records to the rank's JSONL file — called
        at display cadence and lifecycle edges, never per step. A failed
        write is logged and the records dropped: telemetry must never
        turn a flaky shared FS into a training crash."""
        with self._lock:
            buf, self._buf = self._buf, []
            self.flushes += 1
        if not buf:
            return
        lines = []
        for rec in buf:
            try:
                # no default= fallback: a device array (or any
                # non-host value) in a payload must fail HERE, loudly,
                # not silently sync the device at flush time
                lines.append(json.dumps(rec))
            except TypeError as e:
                self.log(
                    f"TELEMETRY: dropping unserializable "
                    f"{rec.get('kind')!r} event: {e}"
                )
        if not lines:
            # every buffered record was dropped: writing would leave a
            # bare blank line that breaks strict JSONL readers
            return
        try:
            os.makedirs(self.events_dir, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            self.writes += 1
        except OSError as e:
            self.log(f"TELEMETRY: could not write {self.path}: {e}")

    def close(self) -> None:
        self.flush()


def recorder_for_job(model_cfg, cluster_cfg, log=print) -> FlightRecorder | None:
    """Build the job's recorder, or None when telemetry has nowhere to
    write (no workspace) or was explicitly disabled. Always-on by
    default: a missing ``telemetry`` config block means enabled."""
    tel = getattr(model_cfg, "telemetry", None)
    if tel is not None and not tel.enabled:
        return None
    if cluster_cfg is None or not cluster_cfg.workspace:
        return None
    from ..resilience.coord import process_index

    subfolder = tel.events_subfolder if tel is not None else "events"
    trace_spans = tel.trace_spans if tel is not None else True
    return FlightRecorder(
        os.path.join(cluster_cfg.workspace, subfolder),
        rank=process_index(),
        run_id=config_hash(model_cfg),
        trace_spans=trace_spans,
        log=log,
    )
