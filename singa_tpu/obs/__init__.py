"""Observability: the flight recorder + trace plane (see recorder.py).

The reference's only observability was the Worker display line —
``Performance`` metric averages plus ``TimerInfo`` phase accumulators
printed every display interval (src/worker/worker.cc:350-386). This
package is the fleet-grade replacement: a per-rank structured event log
(every lifecycle event of the resilience runtime, buffered and flushed
at cadence boundaries), span-mode phase timers exported as Chrome-trace
tracks, and the ``profile@K`` trigger bracketing steps with
``jax.profiler`` traces. ``singa_tpu/tools/trace.py`` merges the
per-rank logs into one Perfetto-loadable ``trace.json``.
"""

from .recorder import FlightRecorder, config_hash, recorder_for_job

__all__ = ["FlightRecorder", "config_hash", "recorder_for_job"]
