"""comm: the real wire under the fleet's transport seam.

The reference's entire cross-process plane was a socket transport —
ZeroMQ PUSH/PULL shipping parameters and activations between workers
and servers (src/worker/neuralnet.cc:112-323). This package is its
serving-era reproduction: a TCP transport implementing the SAME
``send/recv/publish/statuses`` API as the fleet's in-process deques and
filesystem mailboxes (serve/fleet/transport.py), so the router, the
block-migration path, and the hosts never know which wire they ride.

  ``wire``    ``SocketTransport``: length-prefixed CRC'd framing,
              per-peer connections with bounded exponential-backoff
              reconnect, send deadlines with explicit timeout
              verdicts, at-least-once redelivery with per-sender
              message ids (the importer dedupes — a re-sent migration
              is a bitwise no-op), and status publication as a real
              latest-wins push stream instead of NFS mtime polling.
  ``faults``  the wire-fault layer: ``wire_drop@K`` / ``wire_delay@K``
              / ``wire_dup@K`` / ``wire_torn@K`` / ``wire_partition@K``
              terms riding the resilience fault grammar, so CI drills
              prove every failure ends in a documented verdict —
              retry-then-redeliver, reject-back-to-front-door, or a
              loud peer-death tombstone — never a silent hang or a
              half-applied import.
"""

from .faults import SendVerdict, WIRE_KINDS, WireFaults  # noqa: F401
from .wire import (  # noqa: F401
    FrameError,
    SocketTransport,
    WireError,
    pack_frame,
    read_frame,
)
