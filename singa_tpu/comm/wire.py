"""SocketTransport: TCP under the fleet's ``send/recv/publish/statuses``
seam (serve/fleet/transport.py), built to degrade loudly.

Framing — every frame on the wire is::

    magic "STPW" | version u8 | type u8 | header_len u32 | payload_len
    u64 | header_crc u32 | payload_crc u32 | header JSON | payload

Length-prefixed so one bulk npz migration message is ONE frame (no
chunk protocol, the one-shot-transfer shape of arxiv 1805.08430), CRC'd
(zlib.crc32) so a torn or corrupted frame is REJECTED at the receiver —
the connection closes, no ack returns, and the sender redelivers. Three
frame types: ``MSG`` (a fleet message; acked), ``ACK``, and ``STATUS``
(latest-wins, never acked — a slow consumer can never back up the
feedback loop, the mailbox's discipline kept).

Delivery is AT-LEAST-ONCE with dedupe: every MSG carries a per-sender
monotonic message id; the receiver remembers recent ``(src, id)`` pairs
and acks duplicates WITHOUT re-enqueueing them, so a redelivered
migration is a bitwise no-op at the importer. The sender retries a
failed attempt (connect refused, send/ack deadline, CRC-rejected frame)
up to ``max_retries`` times behind bounded exponential backoff
(``backoff_s * 2**attempt``, capped at ``backoff_cap_s`` — no hot
reconnect loop), then raises ``WireError``: the explicit timeout
verdict. A peer that exhausted a send's budget is SUSPECT —
``dead_peers()`` reports it to the host's liveness watchdog (which
tombstones it, ``peer_death``) until a successful send or a fresh
status heals it (``wire_partition_heal``).

Endpoint addressing: ``addresses`` maps endpoint name -> ``host:port``.
``register(name)`` binds that endpoint's listener here (missing from
the map = auto-bind ``127.0.0.1:0`` and record the chosen port back, so
in-process drills need no pre-picked ports). One instance can host
EVERY endpoint of an in-process drill — messages still ride real TCP
loopback, real frames, real acks — while cross-process each process
registers only its own name.

Lifecycle events (flight recorder, thread-safe): ``wire_connect``,
``wire_send``, ``wire_retry``, ``wire_timeout``, ``wire_redeliver``,
``wire_crc_reject``, ``wire_partition_heal`` — peer + attempt + backoff
detail on each, so ``tools/trace.py --summarize`` reconstructs connect
-> retry -> redeliver -> resume from the merged trace.
"""

from __future__ import annotations

import collections
import json
import socket
import struct
import threading
import time
import zlib

MAGIC = b"STPW"
VERSION = 1
MSG, ACK, STATUS = 1, 2, 3

#: magic, version, type, header_len, payload_len, header_crc, payload_crc
_HEAD = struct.Struct(">4sBBIQII")
MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 31

#: dedupe window per endpoint: remembered (src, id) pairs
DEDUPE_WINDOW = 4096


class FrameError(RuntimeError):
    """A frame could not be read: torn (EOF mid-frame), corrupted (CRC
    or header mismatch), or oversized. ``clean_eof`` marks the one
    benign case — the peer closed between frames."""

    def __init__(self, msg: str, *, clean_eof: bool = False):
        super().__init__(msg)
        self.clean_eof = clean_eof


class WireError(RuntimeError):
    """A send exhausted its retry budget: the explicit timeout verdict.
    Carries the peer and the attempt count so the host's failover path
    can tombstone and re-place without string parsing."""

    def __init__(self, msg: str, *, peer: str, attempts: int):
        super().__init__(msg)
        self.peer = peer
        self.attempts = attempts


# imported AFTER the exception classes: serve.fleet.host imports
# WireError back from this module, so by the time the fleet package
# init re-enters here the names it needs are already bound
from ..serve.fleet.transport import KINDS, Message  # noqa: E402


def pack_frame(ftype: int, header: dict, payload: bytes = b"") -> bytes:
    head = json.dumps(header).encode("utf-8")
    if len(head) > MAX_HEADER:
        raise ValueError(f"frame header {len(head)} bytes > {MAX_HEADER}")
    if len(payload) > MAX_PAYLOAD:
        raise ValueError(f"frame payload {len(payload)} bytes > {MAX_PAYLOAD}")
    return (
        _HEAD.pack(
            MAGIC, VERSION, ftype, len(head), len(payload),
            zlib.crc32(head), zlib.crc32(payload),
        )
        + head
        + payload
    )


def _read_exact(sock, n: int, *, at_boundary: bool = False) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrameError(
                f"EOF after {len(buf)}/{n} bytes",
                clean_eof=at_boundary and not buf,
            )
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock) -> tuple[int, dict, bytes]:
    """-> (type, header, payload); FrameError on EOF / CRC mismatch."""
    raw = _read_exact(sock, _HEAD.size, at_boundary=True)
    magic, version, ftype, hlen, plen, hcrc, pcrc = _HEAD.unpack(raw)
    if magic != MAGIC or version != VERSION:
        raise FrameError(f"bad frame magic/version {magic!r}/{version}")
    if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
        raise FrameError(f"oversized frame (header {hlen}, payload {plen})")
    head = _read_exact(sock, hlen)
    payload = _read_exact(sock, plen) if plen else b""
    if zlib.crc32(head) != hcrc or zlib.crc32(payload) != pcrc:
        raise FrameError("frame CRC mismatch (torn or corrupted)")
    try:
        header = json.loads(head.decode("utf-8"))
    except ValueError as e:
        raise FrameError(f"frame header not JSON: {e}") from None
    return ftype, header, payload


def _parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class SocketTransport:
    """The production wiring of the fleet transport seam (module
    docstring). Drop-in for ``LocalTransport`` / ``Mailbox``."""

    def __init__(self, addresses: dict[str, str] | None = None, *,
                 connect_timeout_s: float = 2.0,
                 send_timeout_s: float = 5.0, max_retries: int = 4,
                 backoff_s: float = 0.05, backoff_cap_s: float = 2.0,
                 liveness_timeout_s: float = 0.0, recorder=None,
                 faults=None):
        self.addresses = dict(addresses or {})
        self.connect_timeout_s = connect_timeout_s
        self.send_timeout_s = send_timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.liveness_timeout_s = liveness_timeout_s
        self.faults = faults
        self._lock = threading.Lock()
        self._inbox: dict[str, collections.deque[Message]] = {}
        self._status: dict[str, dict] = {}
        self._status_ns: dict[str, int] = {}
        #: per-endpoint dedupe window: (src, mid) -> seen
        self._seen: dict[str, set] = {}
        self._seen_order: dict[str, collections.deque] = {}
        self._seq: dict[str, int] = {}
        self._conns: dict[str, socket.socket] = {}
        self._listeners: dict[str, socket.socket] = {}
        self._accepted: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        #: peers whose last MSG send exhausted its retry budget
        self._suspect: set[str] = set()
        #: peers whose status broadcast failed: probe-backoff only,
        #: NEVER suspicion (a latent peer that has not launched yet is
        #: not dead — only a failed MESSAGE send may tombstone)
        self._quiet: dict[str, float] = {}
        self._last_heard: dict[str, float] = {}
        self._closed = False
        self._counters = collections.Counter()
        self._send_ms: dict[str, list[float]] = {}
        self._recorder = None
        self.recorder = recorder

    # -- recorder / fault wiring ---------------------------------------

    @property
    def recorder(self):
        return self._recorder

    @recorder.setter
    def recorder(self, rec) -> None:
        self._recorder = rec
        if self.faults is not None:
            self.faults.plan.recorder = rec
            self.faults.emit = self._event

    def _event(self, kind: str, **payload) -> None:
        self._counters[kind] += 1
        if self._recorder is not None:
            self._recorder.event(kind, **payload)

    # -- endpoint lifecycle --------------------------------------------

    def register(self, name: str) -> None:
        with self._lock:
            self._inbox.setdefault(name, collections.deque())
            self._seen.setdefault(name, set())
            self._seen_order.setdefault(name, collections.deque())
            if name in self._listeners:
                return
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        host, port = _parse_addr(self.addresses.get(name, "127.0.0.1:0"))
        srv.bind((host, port))
        srv.listen(64)
        srv.settimeout(0.2)
        # record the bound port back so in-process peers can dial an
        # auto-assigned endpoint without pre-picked ports
        self.addresses[name] = f"{host}:{srv.getsockname()[1]}"
        with self._lock:
            self._listeners[name] = srv
        t = threading.Thread(
            target=self._accept_loop, args=(srv,),
            name=f"wire-accept-{name}", daemon=True,
        )
        t.start()
        self._threads.append(t)

    def _accept_loop(self, srv) -> None:
        while not self._closed:
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._accepted.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="wire-reader", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn) -> None:
        try:
            while not self._closed:
                try:
                    ftype, header, payload = read_frame(conn)
                except FrameError as e:
                    if not e.clean_eof:
                        # torn/corrupt frame: REJECT — close without
                        # acking so the sender redelivers a clean copy
                        self._event(
                            "wire_crc_reject",
                            src=None, reason=str(e),
                        )
                    return
                except OSError:
                    return
                self._handle_frame(ftype, header, payload, conn)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _heal(self, peer: str, via: str) -> None:
        with self._lock:
            was = peer in self._suspect
            self._suspect.discard(peer)
            self._quiet.pop(peer, None)
        if was:
            self._event("wire_partition_heal", peer=peer, via=via)

    def _handle_frame(self, ftype, header, payload, conn) -> None:
        if ftype == MSG:
            src, dst, mid = header["src"], header["dst"], header["mid"]
            with self._lock:
                self._last_heard[src] = time.monotonic()
            self._heal(src, via="recv")
            key = (src, mid)
            fresh = False
            with self._lock:
                seen = self._seen.setdefault(dst, set())
                if key not in seen:
                    fresh = True
                    seen.add(key)
                    order = self._seen_order.setdefault(
                        dst, collections.deque()
                    )
                    order.append(key)
                    while len(order) > DEDUPE_WINDOW:
                        seen.discard(order.popleft())
                    # enqueue BEFORE acking: once the sender's ack
                    # arrives the message is already receivable
                    self._inbox.setdefault(
                        dst, collections.deque()
                    ).append(Message(header["kind"], src, payload))
            if not fresh:
                # the at-least-once no-op: a redelivered message still
                # acks (the sender may have missed the first ack) but
                # never re-enters the inbox
                self._event(
                    "wire_redeliver", peer=src, mid=mid,
                    msg_kind=header.get("kind"),
                )
            try:
                conn.sendall(pack_frame(ACK, {"mid": mid}))
            except OSError:
                pass  # sender gone; it will redeliver and re-ack
        elif ftype == STATUS:
            name, ns = header.get("name"), int(header.get("ns", 0))
            try:
                status = json.loads(payload.decode("utf-8"))
            except ValueError:
                return
            with self._lock:
                self._last_heard[name] = time.monotonic()
                if ns >= self._status_ns.get(name, 0):
                    self._status_ns[name] = ns
                    self._status[name] = status
            self._heal(name, via="status")
        # stray ACKs on a server conn are ignored

    # -- the send path --------------------------------------------------

    def _connect(self, dst: str, attempt: int) -> socket.socket:
        with self._lock:
            sock = self._conns.get(dst)
        if sock is not None:
            return sock
        addr = self.addresses.get(dst)
        if addr is None:
            raise KeyError(f"unknown destination {dst!r}")
        t0 = time.perf_counter()
        sock = socket.create_connection(
            _parse_addr(addr), timeout=self.connect_timeout_s
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._event(
            "wire_connect", peer=dst, attempt=attempt,
            ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        with self._lock:
            self._conns[dst] = sock
        return sock

    def _drop_conn(self, dst: str) -> None:
        with self._lock:
            sock = self._conns.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    @staticmethod
    def _transmit(sock, frame: bytes, verdict) -> None:
        """Write one frame, applying the fault verdict to THIS attempt
        (retries transmit clean — the verdict burned with the send)."""
        if verdict is None or not verdict:
            sock.sendall(frame)
            return
        if verdict.delay_s > 0:
            time.sleep(verdict.delay_s)
        if verdict.drop:
            return  # vanished on the wire: no bytes, no ack
        if verdict.torn:
            cut = max(_HEAD.size, (len(frame) * 3) // 4)
            torn = bytearray(frame)
            torn[min(cut, len(torn) - 1)] ^= 0xFF
            sock.sendall(bytes(torn))
            return
        sock.sendall(frame)
        if verdict.dup:
            sock.sendall(frame)

    def _await_ack(self, sock, mid: int) -> None:
        deadline = time.monotonic() + self.send_timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("ack deadline")
            sock.settimeout(remaining)
            ftype, header, _ = read_frame(sock)
            if ftype == ACK and header.get("mid") == mid:
                return
            # a stale ack (an earlier duplicate's) — ignore and keep
            # waiting for OURS within the same deadline

    def send(self, dst: str, kind: str, payload: bytes, *,
             src: str) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown message kind {kind!r}")
        if dst not in self.addresses:
            raise KeyError(f"unknown destination {dst!r}")
        with self._lock:
            mid = self._seq[src] = self._seq.get(src, 0) + 1
        frame = pack_frame(
            MSG, {"kind": kind, "src": src, "dst": dst, "mid": mid},
            payload,
        )
        verdict = (
            self.faults.on_send(dst) if self.faults is not None else None
        )
        t0 = time.perf_counter()
        last_err = "unreachable"
        for attempt in range(self.max_retries + 1):
            if self.faults is not None and self.faults.partitioned(dst):
                last_err = "partitioned"
            else:
                try:
                    sock = self._connect(dst, attempt)
                    self._transmit(
                        sock, frame, verdict if attempt == 0 else None
                    )
                    self._await_ack(sock, mid)
                    sock.settimeout(None)
                    self._heal(dst, via="send")
                    self._event(
                        "wire_send", peer=dst, msg_kind=kind, mid=mid,
                        bytes=len(frame), attempt=attempt,
                        ms=round((time.perf_counter() - t0) * 1e3, 3),
                    )
                    with self._lock:
                        self._send_ms.setdefault(dst, []).append(
                            (time.perf_counter() - t0) * 1e3
                        )
                    return
                except (OSError, FrameError) as e:
                    last_err = f"{type(e).__name__}: {e}"
                    self._drop_conn(dst)
            if attempt >= self.max_retries:
                break
            backoff = min(
                self.backoff_s * (2 ** attempt), self.backoff_cap_s
            )
            self._event(
                "wire_retry", peer=dst, attempt=attempt,
                backoff_s=round(backoff, 4), reason=last_err,
            )
            time.sleep(backoff)
        with self._lock:
            self._suspect.add(dst)
        self._event(
            "wire_timeout", peer=dst, msg_kind=kind, mid=mid,
            attempts=self.max_retries + 1, reason=last_err,
        )
        raise WireError(
            f"send to {dst!r} failed after {self.max_retries + 1} "
            f"attempts ({last_err})",
            peer=dst, attempts=self.max_retries + 1,
        )

    # -- recv / status ---------------------------------------------------

    def recv(self, name: str) -> list[Message]:
        """Drain and return every delivered message for ``name``."""
        with self._lock:
            box = self._inbox.get(name)
            if not box:
                return []
            out = list(box)
            box.clear()
        return out

    def publish(self, name: str, status: dict) -> None:
        """Latest-wins, push-style: store locally (covers every
        endpoint sharing this instance) and broadcast best-effort
        STATUS frames to all remote endpoints. Never raises, never
        acks, never retries — a failed broadcast marks the peer QUIET
        (probe backoff) so an idle or unlaunched peer costs one probe
        per interval, not a hot connect loop; suspicion is reserved
        for failed MESSAGE sends."""
        ns = time.time_ns()
        with self._lock:
            local = set(self._listeners)
            if ns >= self._status_ns.get(name, 0):
                self._status_ns[name] = ns
                self._status[name] = dict(status)
        frame = pack_frame(
            STATUS, {"name": name, "ns": ns},
            json.dumps(status).encode("utf-8"),
        )
        probe_after = max(0.2, self.backoff_cap_s)
        now = time.monotonic()
        for peer in sorted(self.addresses):
            if peer == name or peer in local:
                continue
            if self.faults is not None and self.faults.partitioned(peer):
                continue
            with self._lock:
                if self._quiet.get(peer, 0.0) > now:
                    continue
            try:
                sock = self._connect(peer, 0)
                sock.sendall(frame)
            except (OSError, KeyError):
                self._drop_conn(peer)
                with self._lock:
                    self._quiet[peer] = now + probe_after
        self._counters["wire_publish"] += 1

    def statuses(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._status.items()}

    # -- liveness ---------------------------------------------------------

    def dead_peers(self) -> set[str]:
        """Peers the wire believes are gone: a send exhausted its retry
        budget (suspect), or — with ``liveness_timeout_s`` > 0 — a peer
        we HAVE heard from went silent past the timeout. The host's
        watchdog turns these into ``peer_death`` tombstones; a
        successful send or a fresh status heals them."""
        with self._lock:
            dead = set(self._suspect)
            if self.liveness_timeout_s > 0:
                now = time.monotonic()
                dead |= {
                    p for p, t in self._last_heard.items()
                    if now - t > self.liveness_timeout_s
                    and p not in self._listeners
                }
            return dead

    # -- introspection / teardown -----------------------------------------

    def wire_stats(self) -> dict:
        with self._lock:
            return {
                "connects": self._counters.get("wire_connect", 0),
                "sends": self._counters.get("wire_send", 0),
                "retries": self._counters.get("wire_retry", 0),
                "timeouts": self._counters.get("wire_timeout", 0),
                "redeliveries": self._counters.get("wire_redeliver", 0),
                "crc_rejects": self._counters.get("wire_crc_reject", 0),
                "partition_heals": self._counters.get(
                    "wire_partition_heal", 0
                ),
                "send_ms": {
                    peer: sorted(ms) for peer, ms in self._send_ms.items()
                },
            }

    def close(self) -> None:
        self._closed = True
        with self._lock:
            conns = list(self._conns.values()) + self._accepted
            self._conns.clear()
            self._accepted = []
            listeners = list(self._listeners.values())
            self._listeners.clear()
        for s in conns + listeners:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)
