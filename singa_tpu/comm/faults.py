"""Wire faults: break the socket transport on purpose, deterministically.

Rides the resilience fault grammar (resilience/faults.py) — the same
``kind@at`` terms, the same fire-once budget, the same recorder wiring —
but keyed on MESSAGE SEND ORDINALS instead of training steps: the K in
``wire_drop@K`` is the K-th ``send()`` call (1-based) this process
makes, counted across every destination. Status broadcasts do NOT
consume ordinals (their cadence varies with idle ticks, which would
make drills non-deterministic); a partition applies to them anyway.

  wire_drop@K              the K-th send's first attempt vanishes on
                           the wire (written nowhere): no ack, the
                           sender times out and REDELIVERS — the
                           at-least-once proof
  wire_delay@K:ms=N        the K-th send's first attempt stalls N ms
                           before the bytes move — exercises deadlines
                           without tripping them
  wire_dup@K               the K-th send's frame is written TWICE: the
                           receiver must dedupe by message id (a
                           re-delivered migration is a bitwise no-op)
                           and the sender must ignore the stale ack
  wire_torn@K              one byte of the K-th send's frame is
                           flipped: the receiver's CRC rejects it,
                           closes the connection, and the sender
                           redelivers a clean copy
  wire_partition@K[=S][:peer=H]  from the K-th send on, peer H (or the
                           K-th send's destination when no ``peer=``)
                           is unreachable for S seconds (omitted = for
                           good): sends exhaust their retry budget,
                           the peer is tombstoned (``peer_death``),
                           and traffic fails over — the loud verdict

Faults fire on the FIRST attempt of their send only; the retries that
recover from them run clean. ``:peer=H`` scopes drop/delay/dup/torn to
sends addressed to H (the ordinal is still burned only when it fires,
matching the rank qualifier's don't-consume-elsewhere discipline).
"""

from __future__ import annotations

import dataclasses
import threading
import time

#: the wire's fault vocabulary (a subset of resilience.faults.KINDS)
WIRE_KINDS = (
    "wire_drop",
    "wire_delay",
    "wire_dup",
    "wire_torn",
    "wire_partition",
)


@dataclasses.dataclass
class SendVerdict:
    """What the fault layer does to ONE send's first attempt."""

    drop: bool = False
    dup: bool = False
    torn: bool = False
    delay_s: float = 0.0

    def __bool__(self) -> bool:
        return self.drop or self.dup or self.torn or self.delay_s > 0


class WireFaults:
    """The transport's fault hook: ``on_send`` burns one ordinal and
    returns the verdict for that send; ``partitioned`` answers whether
    a peer is currently unreachable (and heals expired partitions).
    ``emit`` is set by the transport so heals become recorder events."""

    def __init__(self, plan, *, clock=time.monotonic):
        self.plan = plan
        self.clock = clock
        self.emit = lambda kind, **payload: None
        self._n = 0
        #: peer -> heal deadline (None = partitioned for good)
        self._partitions: dict[str, float | None] = {}
        self._lock = threading.Lock()

    def on_send(self, dst: str) -> SendVerdict:
        with self._lock:
            self._n += 1
            n = self._n
        v = SendVerdict()
        if self.plan.fire("wire_drop", n, peer=dst):
            v.drop = True
        if self.plan.fire("wire_dup", n, peer=dst):
            v.dup = True
        if self.plan.fire("wire_torn", n, peer=dst):
            v.torn = True
        spec = self.plan.fire("wire_delay", n, peer=dst)
        if spec is not None:
            v.delay_s = (spec.ms or 0) / 1e3
        # a partition names its victim (peer= or this send's dst); it is
        # NOT dst-filtered — the ordinal triggers it, the victim suffers
        spec = self.plan.fire("wire_partition", n)
        if spec is not None:
            victim = spec.peer or dst
            with self._lock:
                self._partitions[victim] = (
                    None if spec.value is None
                    else self.clock() + spec.value
                )
        return v

    def partitioned(self, peer: str) -> bool:
        healed = False
        with self._lock:
            if peer not in self._partitions:
                return False
            until = self._partitions[peer]
            if until is not None and self.clock() >= until:
                del self._partitions[peer]
                healed = True
        if healed:
            self.emit("wire_partition_heal", peer=peer, via="expiry")
            return False
        return True
