// Native LMDB dataset reader (C ABI, loaded via ctypes).
//
// The reference reads Caffe LMDB databases through liblmdb + libprotobuf
// (src/worker/layer.cc:237-328); this is the equivalent native path here:
// it walks an LMDB 0.9 data.mdb B+tree (main DB only, 64-bit LE layout —
// the same subset singa_tpu/data/lmdbio.py reads) and decodes each Caffe
// Datum into dense float32/int32 arrays in one pass, no Python in the
// per-record loop. singa_tpu.data.pipeline.load_lmdb_arrays uses it when
// built and falls back to the pure-Python codec otherwise; tests assert
// both produce identical arrays.
//
// Build: g++ -O2 -shared -fPIC -o liblmdbcodec.so lmdbcodec.cc

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xBEEFC0DE;
constexpr uint32_t kVersion = 1;
constexpr uint64_t kInvalidPage = ~0ULL;
constexpr uint16_t P_BRANCH = 0x01, P_LEAF = 0x02, P_OVERFLOW = 0x04,
                   P_META = 0x08, P_LEAF2 = 0x20;
constexpr uint16_t F_BIGDATA = 0x01, F_SUBDATA = 0x02, F_DUPDATA = 0x04;
constexpr size_t kPageHdr = 16;

struct FileBuf {
  std::vector<uint8_t> data;
  bool ok = false;
  explicit FileBuf(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n >= 0) {
      data.resize(static_cast<size_t>(n));
      ok = n == 0 || std::fread(data.data(), 1, data.size(), f) == data.size();
    }
    std::fclose(f);
  }
};

inline uint16_t rd16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
inline uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline uint64_t rd64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

struct Meta {
  uint64_t psize = 0, root = kInvalidPage, entries = 0, txnid = 0;
  uint16_t flags = 0;
  bool ok = false;
};

// Meta layout after the 16-byte page header: magic u32 | version u32 |
// address u64 | mapsize u64 | MDB_db[2] (48B each: pad u32, flags u16,
// depth u16, branch/leaf/overflow/entries/root u64) | last_pg | txnid.
Meta parse_meta(const uint8_t* buf, size_t len, size_t off) {
  Meta m;
  if (off + kPageHdr + 136 > len) return m;
  const uint8_t* p = buf + off;
  if (!(rd16(p + 10) & P_META)) return m;
  const uint8_t* mm = p + kPageHdr;
  if (rd32(mm) != kMagic || rd32(mm + 4) != kVersion) return m;
  m.psize = rd32(mm + 24);           // free DB md_pad doubles as psize
  const uint8_t* main_db = mm + 24 + 48;
  m.flags = rd16(main_db + 4);
  m.entries = rd64(main_db + 32);  // pad4+flags2+depth2+branch8+leaf8+ovfl8
  m.root = rd64(main_db + 40);
  m.txnid = rd64(mm + 24 + 96 + 8);
  m.ok = true;
  return m;
}

// ------------------------------------------------------ Datum decode ----

bool read_varint(const uint8_t* buf, size_t len, size_t* pos, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = buf[(*pos)++];
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
  }
  return false;
}

struct Datum {
  int32_t channels = 0, height = 0, width = 0, label = 0;
  const uint8_t* pix = nullptr;
  size_t pix_len = 0;
  std::vector<float> floats;
  bool encoded = false;
};

bool decode_datum(const uint8_t* buf, size_t len, Datum* d) {
  size_t pos = 0;
  while (pos < len) {
    uint64_t tag, v;
    if (!read_varint(buf, len, &pos, &tag)) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3), wt = tag & 7;
    if (wt == 0 && (field <= 3 || field == 5 || field == 7)) {
      if (!read_varint(buf, len, &pos, &v)) return false;
      int32_t iv = static_cast<int32_t>(v);
      if (field == 1) d->channels = iv;
      else if (field == 2) d->height = iv;
      else if (field == 3) d->width = iv;
      else if (field == 5) d->label = iv;
      else d->encoded = v != 0;
    } else if (field == 4 && wt == 2) {
      if (!read_varint(buf, len, &pos, &v) || v > len - pos) return false;
      d->pix = buf + pos;
      d->pix_len = v;
      pos += v;
    } else if (field == 6 && wt == 5) {
      if (len - pos < 4) return false;
      float f;
      std::memcpy(&f, buf + pos, 4);
      d->floats.push_back(f);
      pos += 4;
    } else if (field == 6 && wt == 2) {  // packed floats
      if (!read_varint(buf, len, &pos, &v) || v > len - pos || v % 4)
        return false;
      size_t n = v / 4, old = d->floats.size();
      d->floats.resize(old + n);
      std::memcpy(d->floats.data() + old, buf + pos, v);
      pos += v;
    } else {  // unknown field: skip by wire type
      switch (wt) {
        case 0:
          if (!read_varint(buf, len, &pos, &v)) return false;
          break;
        case 1:
          if (len - pos < 8) return false;
          pos += 8;
          break;
        case 2:
          if (!read_varint(buf, len, &pos, &v) || v > len - pos) return false;
          pos += v;
          break;
        case 5:
          if (len - pos < 4) return false;
          pos += 4;
          break;
        default:
          return false;
      }
    }
  }
  return true;
}

// ------------------------------------------------------- tree walker ----

struct Walker {
  const std::vector<uint8_t>& buf;
  uint64_t psize;
  uint64_t npages;         // pages in the file: wrap-safe bounds domain
  uint64_t visit_budget;   // total page visits; bounds corrupt cycles
  int64_t rc = 0;  // first error
  int64_t sample = -1;
  int32_t shape[3] = {0, 0, 0};
  std::vector<float> pixels;
  std::vector<int32_t> labels;

  Walker(const std::vector<uint8_t>& b, uint64_t ps)
      : buf(b), psize(ps), npages(b.size() / ps),
        visit_budget(b.size() / ps + 1) {}

  const uint8_t* page(uint64_t pgno) {
    // division-form check: (pgno+1)*psize can wrap uint64 on crafted pgnos
    if (pgno >= npages) return nullptr;
    return buf.data() + pgno * psize;
  }

  bool value(const uint8_t* val, size_t len) {
    Datum d;
    if (!decode_datum(val, len, &d) || d.encoded) {
      rc = -3;
      return false;
    }
    // overflow-checked C*H*W: corrupt dims must become a clean reject,
    // not signed-overflow UB or a doomed multi-GB resize below
    if (d.channels <= 0 || d.height <= 0 || d.width <= 0) {
      rc = -4;
      return false;
    }
    int64_t n = static_cast<int64_t>(d.channels) * d.height;
    if (n > INT64_MAX / d.width) {
      rc = -4;
      return false;
    }
    n *= d.width;
    if (sample < 0) {
      sample = n;
      shape[0] = d.channels;
      shape[1] = d.height;
      shape[2] = d.width;
    }
    if (n != sample) {
      rc = -5;  // mixed geometry: nothing can batch it; the Python
                // fallback raises the descriptive error
      return false;
    }
    // payload size must match BEFORE the dense arrays grow, so every
    // resize is bounded by bytes actually present in the file
    if (d.pix_len) {
      if (static_cast<int64_t>(d.pix_len) != sample) {
        rc = -5;
        return false;
      }
    } else if (static_cast<int64_t>(d.floats.size()) != sample) {
      rc = -5;
      return false;
    }
    size_t old = pixels.size();
    pixels.resize(old + sample);
    float* dst = pixels.data() + old;
    if (d.pix_len) {
      for (int64_t i = 0; i < sample; ++i)
        dst[i] = static_cast<float>(d.pix[i]);
    } else {
      std::memcpy(dst, d.floats.data(), sample * sizeof(float));
    }
    labels.push_back(d.label);
    return true;
  }

  bool walk(uint64_t pgno, int depth) {
    // a corrupt cyclic tree can't visit more pages than the file holds
    if (depth > 64 || visit_budget-- == 0) {
      rc = -3;
      return false;
    }
    const uint8_t* p = page(pgno);
    if (!p) {
      rc = -3;
      return false;
    }
    uint16_t flags = rd16(p + 10);
    uint16_t lower = rd16(p + 12);
    if (flags & P_LEAF2) {
      rc = -4;
      return false;
    }
    if (lower < kPageHdr || lower > psize) {
      rc = -3;
      return false;
    }
    size_t nkeys = (lower - kPageHdr) >> 1;
    for (size_t i = 0; i < nkeys; ++i) {
      uint16_t off = rd16(p + kPageHdr + 2 * i);
      if (off + 8u > psize) {
        rc = -3;
        return false;
      }
      const uint8_t* node = p + off;
      uint16_t lo = rd16(node), hi = rd16(node + 2), nflags = rd16(node + 4),
               ksize = rd16(node + 6);
      if (flags & P_BRANCH) {
        uint64_t child = static_cast<uint64_t>(lo) |
                         (static_cast<uint64_t>(hi) << 16) |
                         (static_cast<uint64_t>(nflags) << 32);
        if (!walk(child, depth + 1)) return false;
      } else if (flags & P_LEAF) {
        if (nflags & (F_SUBDATA | F_DUPDATA)) {
          rc = -4;
          return false;
        }
        uint64_t dsize =
            static_cast<uint64_t>(lo) | (static_cast<uint64_t>(hi) << 16);
        size_t dstart = off + 8 + ksize;
        if (nflags & F_BIGDATA) {
          if (dstart + 8 > psize) {
            rc = -3;
            return false;
          }
          uint64_t ov = rd64(node + 8 + ksize);
          const uint8_t* op = page(ov);
          if (!op || !(rd16(op + 10) & P_OVERFLOW)) {
            rc = -3;
            return false;
          }
          uint32_t chain = rd32(op + 12);
          // division-form bounds: multiplication could wrap on crafted
          // page counts
          if (chain == 0 || ov >= npages || chain > npages - ov ||
              dsize > static_cast<uint64_t>(chain) * psize - kPageHdr) {
            rc = -3;
            return false;
          }
          if (!value(op + kPageHdr, dsize)) return false;
        } else {
          if (dstart + dsize > psize) {
            rc = -3;
            return false;
          }
          if (!value(node + 8 + ksize, dsize)) return false;
        }
      } else {
        rc = -3;
        return false;
      }
    }
    return true;
  }
};

// Heap handle owning the decoded arrays: the caller reads the exposed
// pointers, copies into its own storage, and releases the whole result
// with lc_free_result — no malloc+memcpy duplication of the dataset.
struct Result {
  std::vector<float> pixels;
  std::vector<int32_t> labels;
};

}  // namespace

extern "C" {

// Decode every Datum of an LMDB main database (data.mdb at `path`) into
// dense arrays: float32 pixels ((N, C, H, W) order, uint8 payloads
// widened like the reference's cast, layer.cc:390-400) and int32 labels.
// shape receives (C, H, W); *handle_out receives an opaque owner the
// caller must release with lc_free_result after copying out of
// *pixels_out / *labels_out. Returns the record count, or <0: -1
// open/alloc, -2 empty, -3 corrupt, -4 unsupported feature, -5 mixed
// geometry (callers fall back to the Python codec on any error).
int64_t lc_load_dataset(const char* path, void** handle_out,
                        float** pixels_out, int32_t** labels_out,
                        int32_t* shape_out) try {
  FileBuf fb(path);
  if (!fb.ok || fb.data.size() < 2 * 512) return -1;
  Meta m0 = parse_meta(fb.data.data(), fb.data.size(), 0);
  Meta best = m0;
  if (m0.ok) {
    Meta m1 = parse_meta(fb.data.data(), fb.data.size(), m0.psize);
    if (m1.ok && m1.txnid > m0.txnid) best = m1;
  } else {
    for (uint64_t ps : {4096u, 8192u, 16384u, 32768u, 65536u}) {
      Meta m1 = parse_meta(fb.data.data(), fb.data.size(), ps);
      if (m1.ok && m1.psize == ps) {
        best = m1;
        break;
      }
    }
  }
  if (!best.ok) return -3;
  if (best.psize < 512 || (best.psize & (best.psize - 1))) return -3;
  if (best.flags & ~0x08) return -4;  // dupsort/sub-databases
  if (best.root == kInvalidPage) return -2;

  Walker w(fb.data, best.psize);
  if (!w.walk(best.root, 0)) return w.rc ? w.rc : -3;
  if (w.labels.empty()) return -2;

  Result* res = new Result{std::move(w.pixels), std::move(w.labels)};
  *handle_out = res;
  *pixels_out = res->pixels.data();
  *labels_out = res->labels.data();
  shape_out[0] = w.shape[0];
  shape_out[1] = w.shape[1];
  shape_out[2] = w.shape[2];
  return static_cast<int64_t>(res->labels.size());
} catch (...) {
  // bad_alloc on huge/sparse files etc. must not cross the C ABI —
  // report failure and let the Python reader take over
  return -1;
}

void lc_free_result(void* handle) {
  delete static_cast<Result*>(handle);
}

}  // extern "C"
