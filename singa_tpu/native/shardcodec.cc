// Native shard/record codec (C ABI, loaded via ctypes).
//
// The reference keeps its whole data layer in C++ — the shard record file
// (src/utils/shard.cc), the protobuf Record codec (src/proto/model.proto:
// 279-305 via libprotobuf), and the dataset->shard loader
// (tools/data_loader/). This file is the TPU-native framework's equivalent
// native path: it scans/loads/writes shard.dat files and encodes/decodes
// the proto2 Record wire format without Python in the per-record loop.
// singa_tpu.data.pipeline uses it when built (singa_tpu/native/__init__.py
// compiles it on demand with g++) and falls back to the pure-Python codec
// otherwise; tests assert the two produce byte-identical files.
//
// Wire format recap (shard.cc:49-67): repeated tuples
//   [u64 LE keylen][key][u64 LE vallen][val]
// where val is a proto2 Record{type=0, image={shape*, label, pixel|data*}}.
//
// Build: g++ -O2 -shared -fPIC -o libshardcodec.so shardcodec.cc

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- io ----

struct FileBuf {
  std::vector<uint8_t> data;
  bool ok = false;
  explicit FileBuf(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n >= 0) {
      data.resize(static_cast<size_t>(n));
      ok = n == 0 || std::fread(data.data(), 1, data.size(), f) == data.size();
    }
    std::fclose(f);
  }
};

inline uint64_t read_u64le(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // build targets are little-endian, like the ref
  return v;
}

inline void put_u64le(std::vector<uint8_t>& out, uint64_t v) {
  uint8_t b[8];
  std::memcpy(b, &v, 8);
  out.insert(out.end(), b, b + 8);
}

// ------------------------------------------------------------- varint ----

bool read_varint(const uint8_t* buf, size_t len, size_t* pos, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = buf[(*pos)++];
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
  }
  return false;
}

void write_varint(std::vector<uint8_t>& out, uint64_t v) {
  while (true) {
    uint8_t b = v & 0x7F;
    v >>= 7;
    if (v) {
      out.push_back(b | 0x80);
    } else {
      out.push_back(b);
      return;
    }
  }
}

// All length arithmetic uses subtraction-form bounds checks (`v > len - pos`)
// so an adversarial/corrupted u64 length can't wrap the position past
// SIZE_MAX and defeat the check — the Python reader stops gracefully at a
// corrupt tuple and the native path must too.
bool skip_field(const uint8_t* buf, size_t len, size_t* pos, uint32_t wt) {
  uint64_t tmp;
  switch (wt) {
    case 0:
      return read_varint(buf, len, pos, &tmp);
    case 1:
      if (len - *pos < 8) return false;
      *pos += 8;
      return true;
    case 2:
      if (!read_varint(buf, len, pos, &tmp)) return false;
      if (tmp > len - *pos) return false;
      *pos += tmp;
      return true;
    case 5:
      if (len - *pos < 4) return false;
      *pos += 4;
      return true;
    default:
      return false;
  }
}

// ------------------------------------------------------------- record ----

struct Image {
  std::vector<int32_t> shape;
  int32_t label = 0;
  const uint8_t* pixel = nullptr;
  size_t pixel_len = 0;
  std::vector<float> data;
};

bool decode_image(const uint8_t* buf, size_t len, Image* img) {
  size_t pos = 0;
  while (pos < len) {
    uint64_t tag;
    if (!read_varint(buf, len, &pos, &tag)) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3), wt = tag & 7;
    uint64_t v;
    if (field == 1 && wt == 0) {
      if (!read_varint(buf, len, &pos, &v)) return false;
      img->shape.push_back(static_cast<int32_t>(v));
    } else if (field == 1 && wt == 2) {  // packed repeated int32
      if (!read_varint(buf, len, &pos, &v)) return false;
      if (v > len - pos) return false;
      size_t end = pos + v;
      while (pos < end) {
        uint64_t s;
        if (!read_varint(buf, len, &pos, &s)) return false;
        img->shape.push_back(static_cast<int32_t>(s));
      }
    } else if (field == 2 && wt == 0) {
      if (!read_varint(buf, len, &pos, &v)) return false;
      img->label = static_cast<int32_t>(v);
    } else if (field == 3 && wt == 2) {
      if (!read_varint(buf, len, &pos, &v) || v > len - pos) return false;
      img->pixel = buf + pos;
      img->pixel_len = v;
      pos += v;
    } else if (field == 4 && wt == 5) {
      if (len - pos < 4) return false;
      float f;
      std::memcpy(&f, buf + pos, 4);
      img->data.push_back(f);
      pos += 4;
    } else if (field == 4 && wt == 2) {  // packed repeated float
      if (!read_varint(buf, len, &pos, &v) || v > len - pos || v % 4)
        return false;
      size_t n = v / 4;
      size_t old = img->data.size();
      img->data.resize(old + n);
      std::memcpy(img->data.data() + old, buf + pos, v);
      pos += v;
    } else {
      if (!skip_field(buf, len, &pos, wt)) return false;
    }
  }
  return true;
}

bool decode_record(const uint8_t* buf, size_t len, Image* img, bool* found) {
  size_t pos = 0;
  *found = false;
  while (pos < len) {
    uint64_t tag;
    if (!read_varint(buf, len, &pos, &tag)) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3), wt = tag & 7;
    if (field == 2 && wt == 2) {
      uint64_t ln;
      if (!read_varint(buf, len, &pos, &ln) || ln > len - pos) return false;
      if (!decode_image(buf + pos, ln, img)) return false;
      *found = true;
      pos += ln;
    } else {
      if (!skip_field(buf, len, &pos, wt)) return false;
    }
  }
  return *found;
}

// Canonical encoding, byte-identical to singa_tpu.data.records.encode_record
// (unpacked repeateds, ascending field order).
void encode_record(std::vector<uint8_t>& out, const int32_t* shape,
                   int ndim, int32_t label, const uint8_t* pixel,
                   size_t pixel_len) {
  std::vector<uint8_t> img;
  for (int i = 0; i < ndim; ++i) {
    img.push_back(0x08);
    write_varint(img, static_cast<uint32_t>(shape[i]));
  }
  img.push_back(0x10);
  write_varint(img, static_cast<uint32_t>(label));
  if (pixel_len) {
    img.push_back(0x1A);
    write_varint(img, pixel_len);
    img.insert(img.end(), pixel, pixel + pixel_len);
  }
  out.push_back(0x08);  // Record.type = kSingleLabelImage (0)
  write_varint(out, 0);
  out.push_back(0x12);  // Record.image
  write_varint(out, img.size());
  out.insert(out.end(), img.begin(), img.end());
}

// Iterate complete shard tuples; cb returns false to stop early.
template <typename Fn>
size_t for_each_tuple(const std::vector<uint8_t>& buf, Fn cb,
                      uint64_t* valid_end) {
  size_t pos = 0, count = 0, end = 0;
  const uint8_t* p = buf.data();
  while (true) {
    size_t remain = buf.size() - pos;
    if (remain < 8) break;
    uint64_t keylen = read_u64le(p + pos);
    if (keylen > remain - 8 || remain - 8 - keylen < 8) break;
    const uint8_t* key = p + pos + 8;
    uint64_t vallen = read_u64le(p + pos + 8 + keylen);
    size_t val_off = pos + 8 + keylen + 8;
    if (vallen > buf.size() - val_off) break;
    if (!cb(key, keylen, p + val_off, vallen)) {
      end = val_off + vallen;
      break;
    }
    pos = val_off + vallen;
    end = pos;
    ++count;
  }
  if (valid_end) *valid_end = end;
  return count;
}

}  // namespace

// ------------------------------------------------------------- C ABI ----

extern "C" {

// Scan a shard: complete-tuple count and byte offset after the last
// complete tuple (the PrepareForAppend torn-tail boundary, shard.cc:175-206).
// Returns count, or -1 on open/read failure.
int64_t sc_scan(const char* path, uint64_t* valid_end) {
  FileBuf fb(path);
  if (!fb.ok) return -1;
  return static_cast<int64_t>(for_each_tuple(
      fb.data, [](const uint8_t*, size_t, const uint8_t*, size_t) {
        return true;
      },
      valid_end));
}

// Decode the whole shard in ONE file read: the first record fixes the
// sample geometry, every record is decoded into library-allocated dense
// arrays (float32 pixels — uint8 payloads widened, the reference's cast
// dance at layer.cc:390-400 — and int32 labels). Caller must release both
// arrays with sc_free. Returns records decoded, or <0 on error (-5 = a
// record's payload size mismatched the first record's, the
// uniform-dataset contract — callers fall back to the Python codec).
int64_t sc_load_dataset_alloc(const char* path, float** pixels_out,
                              int32_t** labels_out, int32_t* shape,
                              int32_t shape_cap, int32_t* ndim) try {
  FileBuf fb(path);
  if (!fb.ok) return -1;
  std::vector<float> pixels;
  std::vector<int32_t> labels;
  int64_t sample = -1;
  int64_t rc = 0;
  for_each_tuple(
      fb.data,
      [&](const uint8_t*, size_t, const uint8_t* val, size_t vallen) {
        Image img;
        bool found;
        if (!decode_record(val, vallen, &img, &found)) {
          rc = -3;
          return false;
        }
        if (sample < 0) {  // first record defines the geometry
          if (static_cast<int32_t>(img.shape.size()) > shape_cap ||
              img.shape.empty()) {
            rc = -4;
            return false;
          }
          *ndim = static_cast<int32_t>(img.shape.size());
          sample = 1;
          for (size_t i = 0; i < img.shape.size(); ++i) {
            int32_t d = img.shape[i];
            // corrupt dims must fail cleanly: d <= 0 and the
            // overflow-checked product keep `sample` well-defined
            // (a fuzzed shape once drove resize() into bad_alloc and
            // aborted the embedding process before the payload check
            // below was hoisted above the allocation)
            if (d <= 0 || sample > INT64_MAX / d) {
              rc = -4;
              return false;
            }
            shape[i] = d;
            sample *= d;
          }
        }
        // validate the payload size BEFORE growing the dense arrays:
        // a mismatched record must cost nothing, and after this check
        // every resize is bounded by bytes actually present on disk
        if (img.pixel_len) {
          if (static_cast<int64_t>(img.pixel_len) != sample) {
            rc = -5;
            return false;
          }
        } else if (static_cast<int64_t>(img.data.size()) != sample) {
          rc = -5;
          return false;
        }
        size_t old = pixels.size();
        pixels.resize(old + sample);
        float* dst = pixels.data() + old;
        if (img.pixel_len) {
          for (int64_t i = 0; i < sample; ++i)
            dst[i] = static_cast<float>(img.pixel[i]);
        } else {
          std::memcpy(dst, img.data.data(), sample * sizeof(float));
        }
        labels.push_back(img.label);
        return true;
      },
      nullptr);
  if (rc < 0) return rc;
  if (labels.empty()) return -2;
  float* p = static_cast<float*>(std::malloc(pixels.size() * sizeof(float)));
  int32_t* l =
      static_cast<int32_t*>(std::malloc(labels.size() * sizeof(int32_t)));
  if (!p || !l) {
    std::free(p);
    std::free(l);
    return -1;
  }
  std::memcpy(p, pixels.data(), pixels.size() * sizeof(float));
  std::memcpy(l, labels.data(), labels.size() * sizeof(int32_t));
  *pixels_out = p;
  *labels_out = l;
  return static_cast<int64_t>(labels.size());
} catch (...) {
  // NO C++ exception may escape the C ABI — it would std::terminate
  // the embedding Python process (observed: FileBuf fed a directory
  // path resizes to ftell's bogus LONG_MAX and throws bad_alloc).
  // Surface as a decode error; callers fall back to the Python codec.
  return -6;
}

void sc_free(void* p) { std::free(p); }

// Encode + append n uint8 images as Records with zero-padded index keys
// (matching singa_tpu.data.loader.write_records). start_index offsets the
// keys so kAppend resumes where a crashed run stopped. Truncates the file
// at valid_end first (torn-tail recovery) when appending. Returns records
// written, or <0 on error.
int64_t sc_write_records(const char* path, const uint8_t* images,
                         const int32_t* labels, int64_t n,
                         const int32_t* shape, int32_t ndim,
                         int64_t start_index, int32_t append) {
  int64_t sample = 1;
  for (int32_t i = 0; i < ndim; ++i) sample *= shape[i];

  if (append) {
    // drop a torn final tuple before continuing (PrepareForAppend)
    uint64_t valid_end = 0;
    if (sc_scan(path, &valid_end) >= 0 &&
        truncate(path, static_cast<off_t>(valid_end)) != 0) {
      return -1;
    }
  }
  FILE* f = std::fopen(path, append ? "ab" : "wb");
  if (!f) return -1;

  std::vector<uint8_t> out;
  out.reserve(static_cast<size_t>(n) * (sample + 64));
  char keybuf[32];
  for (int64_t i = 0; i < n; ++i) {
    int keylen =
        std::snprintf(keybuf, sizeof(keybuf), "%08lld",
                      static_cast<long long>(start_index + i));
    std::vector<uint8_t> rec;
    encode_record(rec, shape, ndim, labels[i],
                  images + i * sample, static_cast<size_t>(sample));
    put_u64le(out, static_cast<uint64_t>(keylen));
    out.insert(out.end(), keybuf, keybuf + keylen);
    put_u64le(out, rec.size());
    out.insert(out.end(), rec.begin(), rec.end());
  }
  size_t written = std::fwrite(out.data(), 1, out.size(), f);
  // fsync for crash durability — the torn-tail recovery contract assumes at
  // most the final tuple is lost, which page-cache-only writes would break
  // (the Python ShardWriter.flush fsyncs for the same reason)
  bool ok = written == out.size() && std::fflush(f) == 0 &&
            fsync(fileno(f)) == 0;
  std::fclose(f);
  return ok ? n : -1;
}

}  // extern "C"
