"""Native (C++) data-path acceleration, loaded via ctypes.

The reference's data layer is all C++ (src/utils/shard.cc, the protobuf
Record codec, tools/data_loader/); this package is its native counterpart
here: `shardcodec.cc` scans shard files, decodes/encodes proto2 Records,
and materializes whole datasets without Python in the per-record loop.

The library builds on demand with g++ (one small TU, ~1s) into this
directory; every entry point degrades gracefully to the pure-Python codec
in singa_tpu.data when the toolchain or platform is unavailable, so the
framework stays importable everywhere. `singa_tpu.data.pipeline` routes
through `load_dataset` automatically.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "shardcodec.cc")
_LIB = os.path.join(_DIR, "libshardcodec.so")
_LMDB_SRC = os.path.join(_DIR, "lmdbcodec.cc")
_LMDB_LIB = os.path.join(_DIR, "liblmdbcodec.so")

_lib: ctypes.CDLL | None = None
_tried = False
_lmdb_lib: ctypes.CDLL | None = None
_lmdb_tried = False


def _build(src: str, lib: str) -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", lib, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load(src: str, lib_path: str) -> ctypes.CDLL | None:
    """Build (if stale) + dlopen one codec library; None if unavailable."""
    if not os.path.exists(lib_path) or os.path.getmtime(
        lib_path
    ) < os.path.getmtime(src):
        if not _build(src, lib_path):
            return None
    try:
        return ctypes.CDLL(lib_path)
    except OSError:
        return None


def get_lib() -> ctypes.CDLL | None:
    """Load (building if needed) the shard codec; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    lib = _load(_SRC, _LIB)
    if lib is None:
        return None
    lib.sc_scan.restype = ctypes.c_int64
    lib.sc_scan.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.sc_load_dataset_alloc.restype = ctypes.c_int64
    lib.sc_load_dataset_alloc.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.sc_free.restype = None
    lib.sc_free.argtypes = [ctypes.c_void_p]
    lib.sc_write_records.restype = ctypes.c_int64
    lib.sc_write_records.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    _lib = lib
    return _lib


def get_lmdb_lib() -> ctypes.CDLL | None:
    """Load (building if needed) the LMDB codec; None if unavailable."""
    global _lmdb_lib, _lmdb_tried
    if _lmdb_lib is not None or _lmdb_tried:
        return _lmdb_lib
    _lmdb_tried = True
    lib = _load(_LMDB_SRC, _LMDB_LIB)
    if lib is None:
        return None
    lib.lc_load_dataset.restype = ctypes.c_int64
    lib.lc_load_dataset.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.lc_free_result.restype = None
    lib.lc_free_result.argtypes = [ctypes.c_void_p]
    _lmdb_lib = lib
    return _lmdb_lib


def load_lmdb_dataset(path: str) -> tuple[np.ndarray, np.ndarray] | None:
    """Walk + decode a whole Caffe LMDB natively (the reference's
    liblmdb/libprotobuf path, layer.cc:237-328). -> (images float32
    (N, C, H, W), labels int32 (N,)), or None when the native path can't
    serve it — the caller falls back to singa_tpu.data.lmdbio, which
    either decodes (dupsort-free DBs, no toolchain needed) or raises the
    descriptive error (mixed per-record geometry)."""
    lib = get_lmdb_lib()
    if lib is None:
        return None
    handle = ctypes.c_void_p()
    pixels_p = ctypes.POINTER(ctypes.c_float)()
    labels_p = ctypes.POINTER(ctypes.c_int32)()
    shape_buf = (ctypes.c_int32 * 3)()
    count = lib.lc_load_dataset(
        path.encode(), ctypes.byref(handle), ctypes.byref(pixels_p),
        ctypes.byref(labels_p), shape_buf,
    )
    if count <= 0:
        return None
    try:
        shape = tuple(shape_buf[i] for i in range(3))
        sample = int(np.prod(shape))
        images = np.ctypeslib.as_array(pixels_p, (int(count), sample)).copy()
        labels = np.ctypeslib.as_array(labels_p, (int(count),)).copy()
    finally:
        lib.lc_free_result(handle)
    return images.reshape((int(count), *shape)), labels


def available() -> bool:
    return get_lib() is not None


def scan(path: str) -> tuple[int, int] | None:
    """(complete_tuple_count, valid_end_offset), or None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    end = ctypes.c_uint64(0)
    n = lib.sc_scan(path.encode(), ctypes.byref(end))
    if n < 0:
        return None
    return int(n), int(end.value)


def load_dataset(path: str) -> tuple[np.ndarray, np.ndarray] | None:
    """Decode all records of a uniform-shape shard in native code.

    One file read end-to-end: the library scans, decodes, and returns
    malloc'd dense arrays which are copied into numpy and freed.
    -> (images float32 (N, *shape), labels int32 (N,)), or None when the
    native path can't serve this shard (falls back to Python — e.g. mixed
    per-record shapes).
    """
    lib = get_lib()
    if lib is None:
        return None
    pixels_p = ctypes.POINTER(ctypes.c_float)()
    labels_p = ctypes.POINTER(ctypes.c_int32)()
    shape_buf = (ctypes.c_int32 * 8)()
    ndim = ctypes.c_int32(0)
    count = lib.sc_load_dataset_alloc(
        path.encode(),
        ctypes.byref(pixels_p),
        ctypes.byref(labels_p),
        shape_buf,
        8,
        ctypes.byref(ndim),
    )
    if count <= 0:
        return None  # absent/empty/non-uniform: Python path handles it
    try:
        shape = tuple(shape_buf[i] for i in range(ndim.value))
        sample = int(np.prod(shape))
        images = np.ctypeslib.as_array(pixels_p, (int(count), sample)).copy()
        labels = np.ctypeslib.as_array(labels_p, (int(count),)).copy()
    finally:
        lib.sc_free(pixels_p)
        lib.sc_free(labels_p)
    return images.reshape((int(count), *shape)), labels


def write_records(
    path: str,
    images: np.ndarray,
    labels: np.ndarray,
    start_index: int = 0,
    append: bool = False,
) -> int | None:
    """Encode + write uint8 image records natively; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    images = np.ascontiguousarray(images, dtype=np.uint8)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    shape = (ctypes.c_int32 * (images.ndim - 1))(*images.shape[1:])
    n = lib.sc_write_records(
        path.encode(),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(images),
        shape,
        images.ndim - 1,
        start_index,
        1 if append else 0,
    )
    return int(n) if n >= 0 else None
