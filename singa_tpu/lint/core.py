"""netlint core: diagnostics, rule registry, renderers.

The reference validated nothing statically — a bad ``srclayers`` edge or an
indivisible partition dim only surfaced as a worker crash deep inside
NeuralNet::ConstructNeuralNet / PartitionNeuralNet (reference:
src/worker/neuralnet.cc:72-323). netlint moves that whole failure class to
*before* execution: passes walk parsed configs (and, for the JAX-hazard
rules, the package's own source) and emit ``Diagnostic`` records instead of
raising on the first problem, so one run reports every issue in a job file.

Severities:
  ERROR   — the job cannot run correctly; CLI exits non-zero.
  WARNING — runs, but with a documented degradation (e.g. the indivisible
            kLayerPartition dim that silently pads/replicates). Exit 0
            unless ``--strict``.
  INFO    — advisory (e.g. the kGaussain [sic] spelling note).

Every rule registers itself in ``RULES`` with its code, default severity,
and a one-line doc — ``python -m singa_tpu.tools.lint --list-rules`` renders
the table, making the rule set executable documentation of the system's
invariants.
"""

from __future__ import annotations

import dataclasses
import json

ERROR = "ERROR"
WARNING = "WARNING"
INFO = "INFO"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclasses.dataclass(frozen=True)
class Fix:
    """A machine-applicable single-token rewrite: replace ``old`` at
    (1-based) ``line``/``col`` of ``path`` with ``new``. Only attached
    when the fix is unambiguous (exactly one did-you-mean candidate)
    and the token's exact span is known — ``tools/lint.py --fix``
    re-verifies the text at the span before touching the file."""

    path: str
    line: int
    col: int
    old: str
    new: str


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: machine code + severity + location + message."""

    code: str
    severity: str
    loc: str  # "path", "path:layer=name", or "path:LINE:COL"
    msg: str
    fix_hint: str = ""
    #: optional machine-applicable rewrite (--fix); None = advisory only
    fix: Fix | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    """Registry entry: metadata for one diagnostic code."""

    code: str
    severity: str
    doc: str


#: code -> Rule; populated by ``rule()`` at import time of the pass modules
RULES: dict[str, Rule] = {}


def rule(code: str, severity: str, doc: str) -> Rule:
    """Register (or fetch) a rule. Codes are stable API: tests and CI
    suppressions key on them, so never renumber."""
    assert severity in _SEVERITY_ORDER, severity
    r = Rule(code, severity, doc)
    existing = RULES.get(code)
    if existing is not None:
        assert existing == r, f"conflicting registration for {code}"
        return existing
    RULES[code] = r
    return r


class Collector:
    """Accumulates diagnostics for one lint run.

    ``ignore`` drops codes entirely (the CLI's --ignore). ``emit`` uses the
    rule's registered default severity unless overridden.
    """

    def __init__(self, ignore: set[str] | None = None):
        self.diagnostics: list[Diagnostic] = []
        self.ignore = ignore or set()

    def emit(
        self,
        r: Rule,
        loc: str,
        msg: str,
        *,
        fix_hint: str = "",
        severity: str | None = None,
        fix: Fix | None = None,
    ) -> None:
        if r.code in self.ignore:
            return
        self.diagnostics.append(
            Diagnostic(
                r.code, severity or r.severity, loc, msg, fix_hint, fix
            )
        )

    # ---------------- summary ----------------

    def count(self, severity: str) -> int:
        return sum(1 for d in self.diagnostics if d.severity == severity)

    def has_errors(self, *, strict: bool = False) -> bool:
        if strict:
            return any(
                d.severity in (ERROR, WARNING) for d in self.diagnostics
            )
        return any(d.severity == ERROR for d in self.diagnostics)

    def sorted(self) -> list[Diagnostic]:
        return sorted(
            self.diagnostics,
            key=lambda d: (_SEVERITY_ORDER[d.severity], d.loc, d.code),
        )


# --------------------------------------------------------------------------
# renderers
# --------------------------------------------------------------------------


def render_text(diags: list[Diagnostic]) -> str:
    """One line per finding, grep-friendly:
    ``SEVERITY CODE loc: msg [hint: ...]``"""
    lines = []
    for d in diags:
        line = f"{d.severity:<7} {d.code} {d.loc}: {d.msg}"
        if d.fix_hint:
            line += f" [hint: {d.fix_hint}]"
        lines.append(line)
    return "\n".join(lines)


def render_json(diags: list[Diagnostic]) -> str:
    """Machine-readable dump for CI annotation tooling."""
    return json.dumps(
        {
            "diagnostics": [d.to_dict() for d in diags],
            "counts": {
                s: sum(1 for d in diags if d.severity == s)
                for s in (ERROR, WARNING, INFO)
            },
        },
        indent=2,
    )


def render_rule_table() -> str:
    """--list-rules output: the invariant catalogue."""
    lines = ["CODE     SEVERITY  DESCRIPTION"]
    for code in sorted(RULES):
        r = RULES[code]
        lines.append(f"{code:<8} {r.severity:<9} {r.doc}")
    return "\n".join(lines)
