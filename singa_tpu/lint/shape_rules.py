"""netlint pass family 1b: build-based shape/dtype + param checks.

These go one step deeper than ``net_rules``: the net is actually built
(layer ``setup`` = shape inference, exactly what the worker would run) and
the whole forward pass is traced *abstractly* with ``jax.eval_shape`` — no
FLOP executes, no device memory is touched, but every dot-product dimension
mismatch, dtype surprise, or broken layer contract in the traced path
surfaces as a diagnostic instead of a crash minutes into a pod job.

Building a net opens its data sources (data layers learn their sample
shape from the first record, reference layer.cc:662-672), so when the
shards aren't present — the usual case for linting a conf checked into a
repo — the pass degrades to an INFO note rather than a false ERROR.
"""

from __future__ import annotations

import jax

from ..config.schema import ConfigError, ModelConfig
from ..graph.builder import Net, active_phases, build_net
from .core import Collector, ERROR, INFO, rule
from .net_rules import SHD001

SHP000 = rule(
    "SHP000", INFO, "shape pass skipped: data source not found"
)
SHP001 = rule("SHP001", ERROR, "net construction / shape inference failed")
SHP002 = rule(
    "SHP002", ERROR, "abstract forward trace (jax.eval_shape) failed"
)
PRM001 = rule("PRM001", ERROR, "duplicate qualified param name")
PRM002 = rule("PRM002", ERROR, "share_param references an unknown param")
PRM003 = rule(
    "PRM003", ERROR, "shared param's shape differs from its owner's"
)
SHD002 = rule(
    "SHD002", ERROR, "param neuron/expert axis inconsistent with its shape"
)


def _collect_specs(net: Net, path: str, col: Collector) -> dict:
    """All param specs with PRM001 dupes reported (Net.param_specs
    fail-fasts on the first dupe; lint reports each)."""
    specs: dict = {}
    for layer in net.layers:
        for name, spec in layer.param_specs().items():
            if name in specs:
                col.emit(
                    PRM001,
                    f"{path} (layer {layer.name!r})",
                    f"param {name!r} already declared by another layer",
                )
            else:
                specs[name] = spec
    return specs


def _share_rules(specs: dict, path: str, col: Collector) -> bool:
    """PRM002/PRM003 over owner links; returns False when a link is so
    broken the abstract trace would KeyError."""
    ok = True
    for name, spec in specs.items():
        if spec.owner is None:
            continue
        owner = specs.get(spec.owner)
        if owner is None:
            col.emit(
                PRM002,
                f"{path} (param {name!r})",
                f"share_param owner {spec.owner!r} is not a declared "
                "param",
                fix_hint="share_param entries name the owner as "
                "'<layer>/<param>'",
            )
            ok = False
        elif tuple(owner.shape) != tuple(spec.shape):
            col.emit(
                PRM003,
                f"{path} (param {name!r})",
                f"shape {tuple(spec.shape)} != owner {spec.owner!r} "
                f"shape {tuple(owner.shape)}",
            )
    return ok


def _sharding_rules_built(
    net: Net,
    widths: dict[str, int],
    path: str,
    col: Collector,
    seen: set[str],
) -> None:
    """Precise SHD001/SHD002 from the inferred ParamSpecs — the same
    divisibility condition parallel/shardings._param_layout applies when
    it chooses pad-storage (model axis) or replicate (expert axis).
    ``seen`` dedups params across phases: geometry is phase-independent,
    but each phase can hold live layers every other phase excludes, so
    the caller runs this on every built phase."""
    nmodel = widths.get("model", 1)
    nexpert = widths.get("expert", 1)
    for layer in net.layers:
        for name, spec in layer.param_specs().items():
            if name in seen:
                continue
            seen.add(name)
            ndim = len(spec.shape)
            for label, axis, width, fallback in (
                ("neuron_axis", spec.neuron_axis, nmodel, "pads storage"),
                ("expert_axis", spec.expert_axis, nexpert, "replicates"),
            ):
                if axis is None:
                    continue
                if not 0 <= axis < ndim:
                    col.emit(
                        SHD002,
                        f"{path} (param {name!r})",
                        f"{label} {axis} out of range for shape "
                        f"{tuple(spec.shape)}",
                    )
                    continue
                if label == "neuron_axis" and layer.partition_dim != 1:
                    continue  # not kLayerPartition: stays replicated
                if width > 1 and spec.shape[axis] % width:
                    col.emit(
                        SHD001,
                        f"{path} (param {name!r})",
                        f"dim {axis} of shape {tuple(spec.shape)} not "
                        f"divisible by {label.split('_')[0]} axis "
                        f"{width}: {fallback} instead of sharding evenly",
                        fix_hint=f"size the dim as a multiple of {width}",
                    )


def _abstract_forward(net: Net, specs: dict, phase: str) -> None:
    """Trace Net.forward with ShapeDtypeStructs only (jax.eval_shape):
    full shape/dtype propagation through every layer, zero compute."""
    params = {
        name: jax.ShapeDtypeStruct(tuple(spec.shape), jax.numpy.float32)
        for name, spec in specs.items()
        if spec.owner is None
    }
    batch = {}
    for dl in net.datalayers:
        batch[dl.name] = {
            "image": jax.ShapeDtypeStruct(
                (dl.batchsize, *dl.sample_shape), dl.images.dtype
            ),
            "label": jax.ShapeDtypeStruct(
                (dl.batchsize,), dl.labels.dtype
            ),
        }
    rng = jax.random.PRNGKey(0)

    def fwd(p, b):
        return net.forward(p, b, training=(phase == "kTrain"), rng=rng)

    jax.eval_shape(fwd, params, batch)


def shape_pass(
    model_cfg: ModelConfig,
    path: str,
    col: Collector,
    widths: dict[str, int] | None = None,
) -> bool:
    """Build + abstractly trace every active phase.

    Returns True when at least one phase built (the caller then skips the
    config-level sharding fallback — these checks ran on real specs).
    """
    built_any = False
    shard_seen: set[str] = set()
    for phase in active_phases(model_cfg):
        try:
            net = build_net(model_cfg, phase)
        except OSError as e:
            # data layers open their sources during setup; a conf in a
            # repo usually points at shards that only exist on the
            # training host. Not an error in the conf itself.
            col.emit(
                SHP000,
                f"{path} (phase {phase})",
                f"net not built, data source unavailable: {e}",
            )
            continue
        except ConfigError as e:
            col.emit(
                SHP001, f"{path} (phase {phase})", str(e)
            )
            continue
        except Exception as e:
            # layer setup can raise arbitrary errors on degenerate
            # configs (e.g. stride 0 -> ZeroDivisionError); one bad conf
            # must not abort the diagnostics for every remaining file
            msg = str(e).strip().split("\n")[0][:300]
            col.emit(
                SHP001,
                f"{path} (phase {phase})",
                f"{type(e).__name__}: {msg}",
            )
            continue
        built_any = True
        specs = _collect_specs(net, path, col)
        links_ok = _share_rules(specs, path, col)
        if widths:
            _sharding_rules_built(net, widths, path, col, shard_seen)
        if not links_ok:
            continue
        try:
            _abstract_forward(net, specs, phase)
        except Exception as e:  # eval_shape surfaces arbitrary layer errors
            msg = str(e).strip().split("\n")[0][:300]
            col.emit(
                SHP002,
                f"{path} (phase {phase})",
                f"{type(e).__name__}: {msg}",
            )
    return built_any
