"""netlint: static config/graph/sharding validation + JAX-hazard lint.

Two pass families (ROADMAP "correctness tooling"):

1. **Net/config passes** (``net_rules``, ``shape_rules``) validate parsed
   job confs without executing anything: schema spellings with
   did-you-mean, dangling/cyclic ``srclayers``, phase-exclusion breaks,
   abstract shape/dtype propagation via ``jax.eval_shape``, param sharing,
   and GSPMD divisibility (the statically-decidable sharding errors).
2. **AST passes** (``ast_rules``) lint Python source for JAX hazards:
   host syncs and Python branches inside jitted code, missing
   ``donate_argnums`` on the train-step path, untyped array literals.

CLI: ``python -m singa_tpu.tools.lint <job.conf | dir> [--cluster F]``;
``--self`` lints this package's own source (wired into CI). Rule codes,
severities, and suppression are documented in README "Static analysis
(netlint)" and by ``--list-rules``.
"""

from .core import (  # noqa: F401
    Collector,
    Diagnostic,
    ERROR,
    INFO,
    RULES,
    WARNING,
    render_json,
    render_rule_table,
    render_text,
)
from .cost_model import (  # noqa: F401
    CostReport,
    build_cost_model,
    cost_rules,
    render_cost_report,
)
from .net_rules import (  # noqa: F401
    elastic_rules,
    engine_rules,
    lint_cluster_text,
    lint_model_text,
    ring_rules,
    sharding_rules_static,
    wire_rules,
)
from .shape_rules import shape_pass  # noqa: F401
from .ast_rules import lint_python_file, lint_python_tree  # noqa: F401
