"""netlint pass family 2: AST lint for JAX hazards (stdlib ``ast`` only).

The graph passes check *configs*; these check *code*. JAX's failure modes
are unusually lintable: a host sync (``float()`` / ``.item()`` /
``np.asarray``) on a tracer raises ConcretizationTypeError only when the
jitted path actually runs, a Python ``if`` on a tracer fails the same way,
a forgotten ``donate_argnums`` on the train step silently doubles peak
memory, and an untyped ``jnp.array`` literal can retrigger compilation via
weak-type promotion. All four are visible in the source.

Scope heuristic (documented, deliberately conservative): JAX001/JAX002
only fire inside functions this pass can *prove* are jitted — decorated
with ``@jax.jit`` (directly or through ``partial``), or passed by name to
a ``jax.jit(...)`` call in the same file. Helpers traced indirectly are
not scanned; zero false positives beats exhaustive coverage for an
ERROR-severity rule, and CI runs this over ``singa_tpu/`` itself.

Per-line suppression: ``# netlint: disable=JAX003`` (comma-separate
codes, or omit ``=...`` to silence every rule on that line).
"""

from __future__ import annotations

import ast
import os
import re

from .core import Collector, ERROR, WARNING, rule

JAX000 = rule("JAX000", ERROR, "python file does not parse")
JAX001 = rule(
    "JAX001", ERROR, "host sync on a traced value inside jitted code"
)
JAX002 = rule(
    "JAX002",
    WARNING,
    "Python branch on a tracer-valued expression inside jitted code",
)
JAX003 = rule(
    "JAX003",
    WARNING,
    "jax.jit on the trainer path without donate_argnums",
)
JAX004 = rule(
    "JAX004",
    WARNING,
    "untyped jnp.array literal (weak-type recompilation hazard)",
)
JAX005 = rule(
    "JAX005",
    WARNING,
    "numpy conversion inside jitted code (host round-trip)",
)

# the code list stops at the first non-code token, so trailing prose
# ("# netlint: disable=JAX003 TODO revisit") cannot corrupt the set
_SUPPRESS_RE = re.compile(
    r"#\s*netlint:\s*disable(?:=([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*))?"
)

#: directories no lint walk descends into — walk_source_files below is
#: the one walker that honors it, shared by lint_python_tree and the
#: CLI's path collector / --self so every entry point agrees on what
#: gets scanned
PRUNE_DIRS = frozenset({"__pycache__", ".git"})


def walk_source_files(root: str, exts: tuple[str, ...]):
    """Yield every file under ``root`` with one of the ``exts`` suffixes,
    pruning PRUNE_DIRS, filenames sorted per directory. The single
    PRUNE_DIRS-aware tree walk (this used to be hand-copied in three
    places; ROADMAP correctness-tooling item)."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in PRUNE_DIRS]
        for fname in sorted(filenames):
            if fname.endswith(exts):
                yield os.path.join(dirpath, fname)

#: numpy module aliases whose array constructors force a device->host copy
_HOST_NP = ("np", "numpy", "onp")


def _suppressions(source: str) -> dict[int, set[str] | None]:
    """lineno -> suppressed codes (None = all)."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            codes = m.group(1)
            out[i] = (
                {c.strip() for c in codes.split(",") if c.strip()}
                if codes
                else None
            )
    return out


def _is_jax_jit(node: ast.expr) -> bool:
    """Matches ``jax.jit`` or bare ``jit``."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_decorator(dec: ast.expr) -> ast.Call | None:
    """-> the configuring Call for a jit decorator (for kwarg checks), or
    a synthetic marker for the bare form. Handles ``@jax.jit``,
    ``@jax.jit(...)``, and ``@(functools.)partial(jax.jit, ...)``."""
    if _is_jax_jit(dec):
        return ast.Call(func=dec, args=[], keywords=[])
    if isinstance(dec, ast.Call):
        if _is_jax_jit(dec.func):
            return dec
        func = dec.func
        is_partial = (
            isinstance(func, ast.Name) and func.id == "partial"
        ) or (isinstance(func, ast.Attribute) and func.attr == "partial")
        if is_partial and dec.args and _is_jax_jit(dec.args[0]):
            return dec
    return None


def _contains_jnp(node: ast.AST) -> bool:
    """Does the expression mention ``jnp.<anything>``? Used as the
    tracer-valued marker: jnp calls on static Python values are rare."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "jnp"
        ):
            return True
    return False


def _tracer_names(fn: ast.AST) -> set[str]:
    """Names that hold tracer values inside ``fn``'s body: assigned from
    an expression that mentions ``jnp`` — or one that reads an
    already-tracked name, so aliases and simple derivations
    (``y = x * 2``) stay tracked through assignment chains. A later
    rebind to a plain literal un-tracks the name (the value is a static
    Python scalar again), keeping the false-positive bar: only names the
    pass can PROVE tracer-valued at some point are tracked. Statements
    are visited in source order, so tracking follows dataflow order."""
    tracked: set[str] = set()

    def is_tracer(value: ast.AST) -> bool:
        if _contains_jnp(value):
            return True
        return any(
            isinstance(s, ast.Name)
            and isinstance(s.ctx, ast.Load)
            and s.id in tracked
            for s in ast.walk(value)
        )

    def bind(target: ast.AST, tracer: bool, literal: bool) -> None:
        for t in ast.walk(target):
            if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
                if tracer:
                    tracked.add(t.id)
                elif literal:
                    tracked.discard(t.id)

    def visit(parent: ast.AST) -> None:
        # iter_child_nodes (not ast.walk, which is breadth-first) keeps
        # statements in SOURCE order, so tracking follows dataflow order
        for node in ast.iter_child_nodes(parent):
            value: ast.AST | None = None
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value, targets = node.value, [node.target]
            if value is not None:
                tracer = is_tracer(value)
                if isinstance(node, ast.AugAssign):
                    # x += <expr>: x keeps its prior trackedness unless
                    # the rhs makes it a tracer — never un-track on
                    # augmented literals
                    bind(node.target, tracer, False)
                else:
                    for tgt in targets:
                        bind(tgt, tracer, _is_literal(value))
            visit(node)

    visit(fn)
    return tracked


def _mentions_tracked(node: ast.AST, tracked: set[str]) -> bool:
    return any(
        isinstance(s, ast.Name)
        and isinstance(s.ctx, ast.Load)
        and s.id in tracked
        for s in ast.walk(node)
    )


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    return False


class _FileLinter:
    def __init__(self, path: str, source: str, col: Collector):
        self.path = path
        self.col = col
        self.suppress = _suppressions(source)
        # JAX003 scope: only components at/under the package root count
        # (else a checkout under e.g. /home/trainer/ would flag every
        # module); outside a singa_tpu tree, judge by dir + filename only
        parts = path.replace(os.sep, "/").split("/")
        if "singa_tpu" in parts:
            parts = parts[parts.index("singa_tpu") :]
        else:
            parts = parts[-2:]
        self.on_trainer_path = any("trainer" in p for p in parts)

    def emit(
        self,
        r,
        node: ast.AST,
        msg: str,
        *,
        fix_hint: str = "",
        severity: str | None = None,
        end_line: int | None = None,
    ) -> None:
        # a multi-line construct may carry the disable comment on any of
        # its lines (black puts it after the closing paren). Block
        # statements pass end_line to stop at their header — a comment
        # deep in an if-body must not suppress the enclosing finding.
        if end_line is None:
            end_line = getattr(node, "end_lineno", None) or node.lineno
        for line in range(node.lineno, end_line + 1):
            sup = self.suppress.get(line, "unset")
            if sup is None or (sup != "unset" and r.code in sup):
                return
        self.col.emit(
            r,
            f"{self.path}:{node.lineno}:{node.col_offset}",
            msg,
            fix_hint=fix_hint,
            severity=severity,
        )

    # ---------------- jitted-context discovery ----------------

    def jitted_functions(self, tree: ast.Module) -> list[ast.AST]:
        # ``jax.jit(name)`` resolves the bare name LEXICALLY: only defs
        # whose enclosing scope is an ancestor of the call site count
        # (defs in class bodies: the class body itself only). A flat
        # name-match would scan a never-jitted host helper that happens
        # to share a name with a jitted closure in a sibling method —
        # a false ERROR this pass's contract forbids.
        defs: dict[str, list[tuple[ast.AST, tuple, bool]]] = {}
        jit_calls: list[tuple[ast.Call, tuple]] = []

        def walk(node: ast.AST, path: tuple) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    defs.setdefault(child.name, []).append(
                        (child, path, isinstance(node, ast.ClassDef))
                    )
                    walk(child, path + (id(child),))
                elif isinstance(child, ast.ClassDef):
                    walk(child, path + (id(child),))
                else:
                    if (
                        isinstance(child, ast.Call)
                        and _is_jax_jit(child.func)
                        and child.args
                        and isinstance(child.args[0], ast.Name)
                    ):
                        jit_calls.append((child, path))
                    walk(child, path)

        walk(tree, ())
        jitted: list[ast.AST] = []
        seen: set[int] = set()

        def add(fn: ast.AST) -> None:
            if id(fn) not in seen:
                seen.add(id(fn))
                jitted.append(fn)

        for entries in defs.values():
            for fn, _, _ in entries:
                if any(_jit_decorator(d) for d in fn.decorator_list):
                    add(fn)
        for call, cpath in jit_calls:
            for fn, dpath, in_class in defs.get(call.args[0].id, []):
                visible = (
                    dpath == cpath
                    if in_class
                    else dpath == cpath[: len(dpath)]
                )
                if visible:
                    add(fn)
        return jitted

    # ---------------- rules ----------------

    def check_jitted_body(self, fn: ast.AST) -> None:
        # dataflow widening: names assigned from jnp expressions (or
        # from other tracked names) count as tracer-valued, so aliased
        # escapes like ``y = x * 2; return float(y)`` are caught too
        tracked = _tracer_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._host_sync_rules(node, tracked)
            elif isinstance(node, (ast.If, ast.While)):
                if _contains_jnp(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    self.emit(
                        JAX002,
                        node,
                        f"Python `{kind}` on a jnp-valued expression "
                        "inside jitted code traces only one branch",
                        fix_hint="use jnp.where / lax.cond / lax.select",
                        end_line=getattr(
                            node.test, "end_lineno", None
                        )
                        or node.lineno,
                    )

    def _host_sync_rules(
        self, node: ast.Call, tracked: set[str] = frozenset()
    ) -> None:
        func = node.func
        # x.item() — device sync + concretization error under trace
        if isinstance(func, ast.Attribute) and func.attr == "item":
            self.emit(
                JAX001,
                node,
                ".item() inside jitted code concretizes a tracer",
                fix_hint="return the array and read it outside the jit",
            )
            return
        # float(<jnp expr>) / int(<jnp expr>) — or the same on a name
        # the dataflow pass tracked back to a jnp assignment
        if (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool")
            and node.args
            and (
                _contains_jnp(node.args[0])
                or _mentions_tracked(node.args[0], tracked)
            )
        ):
            self.emit(
                JAX001,
                node,
                f"{func.id}() on a jnp expression inside jitted code "
                "concretizes a tracer",
                fix_hint="keep the value as a jnp array inside the jit",
            )
            return
        # np.asarray / np.array on a non-literal — host round-trip. Its
        # own WARNING code (not JAX001): the argument may turn out to be
        # a static Python value, so ERROR would risk false positives.
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in _HOST_NP
            and node.args
            and not _is_literal(node.args[0])
        ):
            self.emit(
                JAX005,
                node,
                f"{func.value.id}.{func.attr}() inside jitted code pulls "
                "the value to the host",
                fix_hint="use jnp, or hoist the conversion out of the jit",
            )

    def check_jit_callsites(self, tree: ast.Module) -> None:
        """JAX003: train-path jit without donation. Only meaningful where
        step inputs are dead after the call — i.e. trainer modules."""
        if not self.on_trainer_path:
            return

        def check(kwargs: set, node: ast.AST) -> None:
            if not kwargs & {"donate_argnums", "donate_argnames"}:
                self.emit(
                    JAX003,
                    node,
                    "jax.jit without donate_argnums on the trainer path "
                    "keeps both input and output buffers live",
                    fix_hint="donate dead step inputs, or suppress with "
                    "# netlint: disable=JAX003 where inputs are reused",
                )

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func):
                check({kw.arg for kw in node.keywords}, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # decorator forms the Call branch can't see: bare
                # @jax.jit and @partial(jax.jit, ...). @jax.jit(...) IS
                # an ast.Call, so the branch above already covers it.
                for dec in node.decorator_list:
                    cfg = _jit_decorator(dec)
                    if cfg is None or (
                        isinstance(dec, ast.Call)
                        and _is_jax_jit(dec.func)
                    ):
                        continue
                    check({kw.arg for kw in cfg.keywords}, dec)

    def check_array_literals(self, tree: ast.Module) -> None:
        """JAX004: ``jnp.array(<literal>)`` without dtype= is weakly
        typed — inside a jit it can retrigger compilation and silently
        change promotion."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr == "array"
                and isinstance(func.value, ast.Name)
                and func.value.id == "jnp"
            ):
                continue
            if not (node.args and _is_literal(node.args[0])):
                continue
            # dtype may be passed as keyword or as the second positional
            if len(node.args) >= 2 or any(
                kw.arg == "dtype" for kw in node.keywords
            ):
                continue
            self.emit(
                JAX004,
                node,
                "jnp.array on a bare literal is weakly typed",
                fix_hint="pass dtype= explicitly",
            )

def lint_python_file(path: str, col: Collector) -> None:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        # one unreadable file must not abort the rest of the run
        col.emit(JAX000, f"{path}:0:0", f"cannot read: {e}")
        return
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        col.emit(
            JAX000,
            f"{path}:{e.lineno or 0}:0",
            f"file does not parse: {e.msg}",
        )
        return
    linter = _FileLinter(path, source, col)
    for fn in linter.jitted_functions(tree):
        linter.check_jitted_body(fn)
    linter.check_jit_callsites(tree)
    linter.check_array_literals(tree)


def lint_python_tree(root: str, col: Collector) -> int:
    """Lint every .py under ``root``; returns the file count."""
    n = 0
    for path in walk_source_files(root, (".py",)):
        lint_python_file(path, col)
        n += 1
    return n
