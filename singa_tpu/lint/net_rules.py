"""netlint pass family 1: config + graph + sharding rules.

These run on the *parsed text*, never executing a layer: a raw-tree walk
(every-error-at-once schema checking with did-you-mean), then graph rules
over the typed ``ModelConfig`` (the static half of what
NeuralNet::ConstructNeuralNet would crash on at runtime, reference
src/worker/neuralnet.cc:72-110), then cluster-topology and sharding
divisibility checks (the statically-decidable slice of GSPMD layout,
parallel/shardings.py).

Sharding rules need a cluster conf to know the mesh axis widths; model-only
runs skip them. Shape inference (which needs the data sources) lives in
``shape_rules``.
"""

from __future__ import annotations

import difflib
import re
from typing import Any

from ..config import schema, textproto
from ..config.schema import (
    ClusterConfig,
    ConfigError,
    Message,
    ModelConfig,
)
from ..graph.builder import active_phases
from ..graph.kahn import kahn_order
from .core import Collector, ERROR, Fix, INFO, WARNING, rule

# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

CFG000 = rule("CFG000", ERROR, "config does not parse (syntax or schema)")
CFG001 = rule("CFG001", ERROR, "unknown field name (with did-you-mean)")
CFG002 = rule("CFG002", ERROR, "unknown enum value (with did-you-mean)")
CFG003 = rule(
    "CFG003",
    INFO,
    "reference [sic] spelling kGaussain; corrected kGaussian is accepted",
)
NET001 = rule("NET001", ERROR, "srclayers edge references an unknown layer")
NET002 = rule("NET002", ERROR, "cycle in the layer graph")
NET003 = rule(
    "NET003", ERROR, "live layer depends on a layer excluded from its phase"
)
NET004 = rule("NET004", ERROR, "duplicate layer names live in one phase")
CLU001 = rule(
    "CLU001", ERROR, "nprocs_per_group not divisible by nseq*nexperts*npipes"
)
CLU002 = rule("CLU002", ERROR, "nworkers < nprocs_per_group: zero groups")
SHD001 = rule(
    "SHD001",
    WARNING,
    "kLayerPartition neuron dim not divisible by the model axis "
    "(storage is padded / experts replicate instead of sharding)",
)
SHD003 = rule(
    "SHD003", WARNING, "batchsize not divisible by the data axis width"
)
CMM001 = rule(
    "CMM001",
    ERROR,
    "active grad_comm block combined with the replica (async PS) engine",
)
SRV001 = rule(
    "SRV001",
    ERROR,
    "prefix_cache enabled but kv_blocks cannot hold one max-length "
    "prompt, or tail_stride does not tile kv_block_len",
)
FLT001 = rule(
    "FLT001",
    ERROR,
    "fleet topology cannot serve: a prefill-capable host whose "
    "kv_blocks cannot cover one max-length prompt, or a split-role "
    "fleet missing the other half (decode with no prefill-capable "
    "peer, prefill with no decode-capable peer)",
)
KRN001 = rule(
    "KRN001",
    ERROR,
    "fused paged_attention selected with a geometry the compiled "
    "kernel cannot tile",
)
KRN002 = rule(
    "KRN002",
    ERROR,
    "ring grad_allreduce (quantized_ring/q8_hier) without a quantized "
    "grad_comm block, with an un-chunkable data-axis geometry, with a "
    ">1-wide non-data mesh axis the factorization does not cover, "
    "with a broken ring {} two-level geometry (absent axis, "
    "indivisible intra_degree), with a batch-stat (kBatchNorm) net, "
    "or with the replica engine",
)
ELA001 = rule(
    "ELA001",
    ERROR,
    "resume checkpoint's sharded manifest cannot be hosted by the "
    "--cluster mesh (a spec names an axis the mesh lacks, or a dim "
    "has fewer elements than the target axis width — beyond even the "
    "pad/replicate fallback)",
)
WIR001 = rule(
    "WIR001",
    ERROR,
    "socket transport misconfigured: missing/duplicate peer or "
    "frontdoor addresses, non-positive wire timeouts/backoff, or a "
    "send deadline that cannot cover one max-size migration message "
    "(or, with the prefix cache on, one max-prefix cache_ship frame)",
)
ROL001 = rule(
    "ROL001",
    ERROR,
    "live weight rollout infeasible: no checkpoint to ship, a canary "
    "that is not a declared decode-capable host (or names the whole "
    "single-host fleet), degenerate probe/retry knobs, or "
    "dual-resident staged params that overflow the --cluster "
    "device_hbm_bytes budget (cost model)",
)

#: reverse of schema.ENUM_ALIASES: [sic] token -> corrected spelling
_TYPO_NOTES = {v: k for k, v in schema.ENUM_ALIASES.items()}


# ---------------------------------------------------------------------------
# loose schema walk: report every unknown field / enum value, don't fail-fast
# ---------------------------------------------------------------------------


def _line_of(text: str | None, needle: str) -> str:
    """Fallback line locator for callers without a parse span: first
    line containing ``needle`` as a whole token — a bare substring
    scan would attribute 'kGaussain' to a line holding
    'kGaussainSqrtFanIn'. Falls back to substring if no token match.
    The parse tree's own spans (textproto.parse_with_locs) are the
    primary source; this text search only covers needles that never
    were tokens (e.g. messages quoting a converted value)."""
    if not text:
        return ""
    token = re.compile(
        rf"(?<![A-Za-z0-9_]){re.escape(needle)}(?![A-Za-z0-9_])"
    )
    fallback = ""
    for i, line in enumerate(text.splitlines(), 1):
        if token.search(line):
            return str(i)
        if not fallback and needle in line:
            fallback = str(i)
    return fallback


def _loc(
    path: str,
    text: str | None,
    needle: str,
    ctx: str,
    span: tuple[int, int] | None = None,
) -> str:
    """Diagnostic location: ``path:LINE:COL`` from an exact parse span
    when the caller has one (a lookup, not a search), else the
    best-effort ``path:LINE`` text scan."""
    if span is not None:
        base = f"{path}:{span[0]}:{span[1]}"
    else:
        line = _line_of(text, needle)
        base = f"{path}:{line}" if line else path
    return f"{base} ({ctx})" if ctx else base


def walk_raw_config(
    raw: dict[str, list[Any]],
    cls: type[Message],
    path: str,
    col: Collector,
    *,
    text: str | None = None,
    ctx: str = "",
    locs: dict[str, list[textproto.FieldLoc]] | None = None,
    _seen_typos: set[tuple[str, str]] | None = None,
) -> None:
    """Check a textproto parse tree against ``cls``'s field schema,
    emitting CFG001/CFG002/CFG003 for everything wrong (the strict
    ``Message.from_fields`` stops at the first error; lint wants all).
    CFG003 is advisory, so it fires once per (field, spelling) per file
    rather than once per occurrence. ``locs`` is the parallel span tree
    from ``textproto.parse_with_locs`` — when present, diagnostics carry
    exact ``path:LINE:COL`` locations and unambiguous did-you-mean
    suggestions carry a machine-applicable Fix (``--fix``)."""
    if _seen_typos is None:
        _seen_typos = set()
    for fname, occurrences in raw.items():
        flocs = (locs or {}).get(fname, [])

        def span_of(i: int, *, value: bool = False):
            if i < len(flocs):
                fl = flocs[i]
                return fl.value if value else fl.key
            return None

        spec = cls.FIELDS.get(fname)
        if spec is None:
            close = difflib.get_close_matches(fname, cls.FIELDS, n=2)
            hint = f"did you mean {close[0]!r}?" if close else ""
            span = span_of(0)
            fix = None
            if len(close) == 1 and span is not None:
                fix = Fix(path, span[0], span[1], fname, close[0])
            col.emit(
                CFG001,
                _loc(path, text, fname, ctx, span),
                f"unknown field {fname!r} in {cls.__name__}",
                fix_hint=hint,
                fix=fix,
            )
            continue
        if spec.kind == "message":
            pairs = [
                (occ, span_of(i))
                for i, occ in enumerate(occurrences)
            ]
            dicts = [(o, s) for o, s in pairs if isinstance(o, dict)]
            sublocs = [
                flocs[i].sub if i < len(flocs) else None
                for i, occ in enumerate(occurrences)
                if isinstance(occ, dict)
            ]
            if len(dicts) < len(occurrences):
                bad = next(s for o, s in pairs if not isinstance(o, dict))
                col.emit(
                    CFG000,
                    _loc(path, text, fname, ctx, bad),
                    f"field {fname!r} expects a message block",
                )
            if not spec.repeated and len(dicts) > 1:
                # protobuf text-format merge (schema.from_fields): walk
                # the merged tree once, so a required subfield present in
                # any occurrence is not misreported as missing — the loc
                # trees merge the same way, keeping spans aligned
                merged: dict[str, list[Any]] = {}
                merged_locs: dict[str, list] = {}
                for (occ, _), sl in zip(dicts, sublocs):
                    for sub, subvals in occ.items():
                        merged.setdefault(sub, []).extend(subvals)
                        merged_locs.setdefault(sub, []).extend(
                            (sl or {}).get(
                                sub,
                                [textproto.FieldLoc(None)] * len(subvals),
                            )
                        )
                dicts = [(merged, None)]
                sublocs = [merged_locs]
            for (occ, _), sl in zip(dicts, sublocs):
                sub_ctx = fname
                names = occ.get("name")
                if names and isinstance(names[-1], str):
                    sub_ctx = f"{fname} {names[-1]!r}"
                if ctx:
                    sub_ctx = f"{ctx}.{sub_ctx}"
                walk_raw_config(
                    occ,
                    spec.message,
                    path,
                    col,
                    text=text,
                    ctx=sub_ctx,
                    locs=sl,
                    _seen_typos=_seen_typos,
                )
        elif spec.kind == "enum":
            for i, occ in enumerate(occurrences):
                if not isinstance(occ, str):
                    continue
                if occ in spec.enum and occ not in _TYPO_NOTES:
                    continue  # exact member, nothing to say
                vspan = span_of(i, value=True)
                if occ in _TYPO_NOTES and occ in spec.enum:
                    # a [sic] token used where it is actually valid: note
                    # the corrected spelling. Used in the WRONG field it
                    # falls through to the CFG002 membership check below.
                    if (fname, occ) not in _seen_typos:
                        _seen_typos.add((fname, occ))
                        col.emit(
                            CFG003,
                            _loc(path, text, occ, "", vspan),
                            f"{fname}: {occ!r} is the reference's [sic] "
                            f"spelling; the corrected {_TYPO_NOTES[occ]!r} "
                            "is accepted as an alias",
                        )
                    continue
                canonical = schema.ENUM_ALIASES.get(occ, occ)
                if canonical not in spec.enum:
                    vocab = list(spec.enum) + [
                        a
                        for a, t in schema.ENUM_ALIASES.items()
                        if t in spec.enum
                    ]
                    close = difflib.get_close_matches(occ, vocab, n=2)
                    hint = f"did you mean {close[0]!r}?" if close else ""
                    fix = None
                    if len(close) == 1 and vspan is not None:
                        fix = Fix(path, vspan[0], vspan[1], occ, close[0])
                    col.emit(
                        CFG002,
                        _loc(path, text, occ, ctx, vspan),
                        f"{fname}: {occ!r} not in {spec.enum}",
                        fix_hint=hint,
                        fix=fix,
                    )
        else:
            # scalar kinds: report every coercion failure with the exact
            # text the strict parse would use (it stops at the first; the
            # caller dedups by message)
            for i, occ in enumerate(occurrences):
                try:
                    spec.convert(occ, fname)
                except ConfigError as e:
                    col.emit(
                        CFG000,
                        _loc(
                            path, text, str(occ), ctx,
                            span_of(i, value=True),
                        ),
                        str(e),
                    )
    for fname, spec in cls.FIELDS.items():
        if (
            spec.required
            and not spec.repeated
            and spec.default is None
            and fname not in raw
        ):
            col.emit(
                CFG000,
                f"{path} ({ctx})" if ctx else path,
                f"{cls.__name__}: missing required {fname!r}",
            )


# ---------------------------------------------------------------------------
# graph rules (typed ModelConfig)
# ---------------------------------------------------------------------------


def graph_rules(model_cfg: ModelConfig, path: str, col: Collector) -> None:
    """NET001-NET004 over every phase the job will actually build."""
    net_cfg = model_cfg.neuralnet
    if net_cfg is None:
        col.emit(CFG000, path, "model config has no neuralnet block")
        return
    layers = net_cfg.layer
    global_names = {l.name for l in layers}
    seen_dangling: set[tuple[str, str]] = set()
    seen_cycles: set[frozenset] = set()
    for phase in active_phases(model_cfg):
        live = [l for l in layers if phase not in (l.exclude or [])]
        names = [l.name for l in live]
        dupes = sorted({n for n in names if names.count(n) > 1})
        for name in dupes:
            col.emit(
                NET004,
                f"{path} (layer {name!r})",
                f"{len([n for n in names if n == name])} layers named "
                f"{name!r} are all live in phase {phase}",
                fix_hint="add exclude: so at most one survives each "
                "phase the job runs",
            )
        live_names = set(names)
        for l in live:
            for src in l.srclayers:
                if src not in global_names:
                    if (l.name, src) not in seen_dangling:
                        seen_dangling.add((l.name, src))
                        close = difflib.get_close_matches(
                            src, sorted(global_names), n=1
                        )
                        hint = (
                            f"did you mean {close[0]!r}?" if close else ""
                        )
                        col.emit(
                            NET001,
                            f"{path} (layer {l.name!r})",
                            f"srclayers references unknown layer {src!r}",
                            fix_hint=hint,
                        )
                elif src not in live_names:
                    col.emit(
                        NET003,
                        f"{path} (layer {l.name!r})",
                        f"depends on {src!r}, which is excluded from "
                        f"phase {phase} while {l.name!r} is live",
                        fix_hint=f"exclude {l.name!r} from {phase} too, "
                        f"or un-exclude {src!r}",
                    )
        if dupes:
            continue  # cycle check is ill-defined with duplicate names
        stuck = _cycle_members(live, live_names)
        if stuck and frozenset(stuck) not in seen_cycles:
            seen_cycles.add(frozenset(stuck))
            col.emit(
                NET002,
                path,
                f"cycle in the layer graph involving {sorted(stuck)} "
                f"(phase {phase})",
            )


def _cycle_members(live, live_names) -> set[str]:
    """Kahn's-algorithm residue = the layers on (or downstream of) a
    cycle; dangling edges are ignored (NET001 owns those). The core loop
    is shared with builder.topo_sort (graph/kahn.py) — this caller keeps
    only the report-all policy."""
    del live_names  # kahn_order ignores edges to unknown names itself
    _, residue = kahn_order(
        [l.name for l in live], {l.name: l.srclayers for l in live}
    )
    return residue


# ---------------------------------------------------------------------------
# cluster rules
# ---------------------------------------------------------------------------


def cluster_rules(
    cluster_cfg: ClusterConfig, path: str, col: Collector
) -> dict[str, int] | None:
    """CLU001/CLU002; returns the mesh axis widths when the topology is
    coherent (the sharding rules' input), else None. Both checks run —
    a conf broken in both ways gets both diagnostics in one pass."""
    ngroups_err = None
    try:
        cluster_cfg.ngroups
    except ConfigError as e:
        ngroups_err = str(e)
        col.emit(CLU002, path, ngroups_err)
    try:
        widths = cluster_cfg.axis_widths
    except ConfigError as e:
        # axis_widths re-raises the ngroups error when only that one
        # exists; don't report it under two codes
        if str(e) != ngroups_err:
            col.emit(CLU001, path, str(e))
        return None
    return None if ngroups_err else widths


# ---------------------------------------------------------------------------
# engine-compatibility rules (model conf x cluster conf)
# ---------------------------------------------------------------------------


def engine_rules(
    model_cfg: ModelConfig, cluster_cfg: ClusterConfig | None, path: str,
    col: Collector,
) -> None:
    """CMM001 — the static mirror of the trainer-constructor rejection
    (trainer/replica.py ``_supports_grad_comm``): an asynchronous
    cluster with ``nservers > 0`` routes a backprop job to the replica
    engine, whose EASGD/RandomSync protocol owns its own gradient-sync
    math — an active ``grad_comm`` block (quantized mode or bucketized
    overlap) would be rejected at engine construction, so lint says it
    before any pod time is burned. Mirrors the ``zero_update``
    rejection; the CD engine rides the shared seam and is fine."""
    gc = getattr(model_cfg, "grad_comm", None)
    if gc is None or (gc.mode == "exact" and gc.buckets <= 1):
        return
    if (
        cluster_cfg is not None
        and cluster_cfg.nservers > 0
        and not cluster_cfg.synchronous
        and model_cfg.alg != "kContrastiveDivergence"
        and model_cfg.updater is not None
    ):
        col.emit(
            CMM001,
            path,
            f"grad_comm (mode {gc.mode!r}, buckets {gc.buckets}) with an "
            "asynchronous nservers>0 cluster: the replica engine's "
            "EASGD protocol owns its own gradient sync and rejects the "
            "quantize/overlap machinery",
            fix_hint="drop the grad_comm block, or run the synchronous "
            "engine (synchronous: true / nservers: 0)",
        )


# ---------------------------------------------------------------------------
# serving rules (model conf alone)
# ---------------------------------------------------------------------------


def serving_rules(model_cfg: ModelConfig, path: str, col: Collector) -> None:
    """SRV001 — static admission feasibility for a prefix-caching
    serving tier (the shardlint direction: predict the capacity cliff
    before any pod time is burned). serve/kv_pool.KVPool.for_model
    raises at engine construction when ``kv_blocks`` cannot hold even
    ONE full-length sequence plus the trash block; with
    ``prefix_cache`` enabled that failure is doubly wasteful — the
    operator sized the pool for cache wins it can never admit. The
    model's positional window comes from the kEmbedding layer's
    declared ``max_len``; a window left to the data layer's sequence
    length (max_len 0) is not statically decidable and is skipped."""
    srv = getattr(model_cfg, "serving", None)
    if srv is None or srv.prefix_cache is None or not srv.prefix_cache.enabled:
        return
    # partial-tail stride must tile the block: sub-block digests are
    # registered at multiples of tail_stride inside one block, so a
    # stride that does not divide kv_block_len (or is negative) is
    # rejected by PrefixCache at engine construction — say it before
    # any pod time is burned
    stride = getattr(srv.prefix_cache, "tail_stride", 0)
    block_len = max(1, srv.kv_block_len)
    if stride < 0 or (stride and block_len % stride):
        col.emit(
            SRV001,
            path,
            f"serving.prefix_cache.tail_stride {stride} does not tile "
            f"kv_block_len {block_len}: sub-block tail digests land at "
            "multiples of the stride inside one block, so the engine "
            "rejects this geometry at construction",
            fix_hint=f"pick a positive tail_stride dividing "
            f"{block_len} (or 0 to disable partial-tail sharing)",
        )
    if srv.kv_blocks <= 0:
        return  # dense-equivalent sizing always fits one sequence
    window = _declared_window(model_cfg)
    if not window:
        return
    block_len = max(1, srv.kv_block_len)
    need = -(-window // block_len) + 1  # one full sequence + trash block
    if srv.kv_blocks < need:
        col.emit(
            SRV001,
            path,
            f"serving.prefix_cache enabled with kv_blocks "
            f"{srv.kv_blocks} < {need} needed to admit one max-length "
            f"prompt ({window} positions / kv_block_len {block_len} + "
            "the reserved trash block): every admission would raise "
            "before the cache could ever hit",
            fix_hint=f"set kv_blocks >= {need} (or 0 for "
            "dense-equivalent sizing)",
        )


def _declared_window(model_cfg: ModelConfig) -> int:
    """The model's statically-declared positional window (the
    kEmbedding layer's ``max_len``); 0 = not statically decidable
    (window left to the data layer's sequence length)."""
    net_cfg = model_cfg.neuralnet
    if net_cfg is None:
        return 0
    return max(
        (
            l.embedding_param.max_len
            for l in net_cfg.layer
            if l.embedding_param is not None and l.embedding_param.max_len
        ),
        default=0,
    )


def fleet_rules(model_cfg: ModelConfig, path: str, col: Collector) -> None:
    """FLT001 — static mirrors of the fleet-host construction
    rejections (serve/fleet/host.py), SRV001's sibling. Two arms,
    reported independently:

    (a) a host that will run the PREFILL role (explicit ``role:
        prefill``, or ``auto`` — where ranks below ``prefill_hosts``
        always exist, or an explicit prefill ``peers`` entry) with a
        ``serving.kv_blocks`` that cannot cover even ONE max-length
        prompt plus the trash block: every admission would raise
        before a single chunk ran (KVPool.for_model's runtime raise,
        said before any pod time is burned). Skipped when the window
        is not statically decidable, like SRV001.
    (b) a split-role topology missing the other half: every host of
        the lonely role raises at FleetHost construction (a decode
        host with no prefill-capable peer has KV blocks nothing can
        ever fill; a prefill host with no decode-capable peer fills
        sequences that have nowhere to stream). Explicit ``peers``
        entries ARE the topology (rank order, the runtime's
        ``fleet_topology``); without them an explicit single role is
        the whole fleet. ``role: auto`` without peers splits ranks at
        runtime by a host count the model conf cannot see — skipped,
        like SRV001's not-statically-decidable window."""
    fleet = getattr(model_cfg, "fleet", None)
    if fleet is None:
        return
    # (c) elastic sizing that cannot describe a fleet. Explicit peers
    # entries ARE the topology, so max_hosts cannot invent hosts beyond
    # them, and min_hosts cannot exceed whatever is actually declared
    # (peers when present, else max_hosts) — both reject at
    # run_from_conf before any host serves
    if (
        fleet.peers
        and fleet.max_hosts
        and fleet.max_hosts > len(fleet.peers)
    ):
        col.emit(
            FLT001,
            path,
            f"fleet max_hosts {fleet.max_hosts} exceeds the "
            f"{len(fleet.peers)} declared peers entries — peers name "
            "the whole topology, max_hosts cannot invent hosts: the "
            "launch would reject before any host serves",
            fix_hint="declare the extra hosts as peers entries, or "
            "drop max_hosts",
        )
    n_declared = len(fleet.peers or ()) or (fleet.max_hosts or 0)
    if fleet.min_hosts and n_declared and fleet.min_hosts > n_declared:
        col.emit(
            FLT001,
            path,
            f"fleet min_hosts {fleet.min_hosts} exceeds the declared "
            f"topology ({n_declared} host(s) from "
            f"{'peers' if fleet.peers else 'max_hosts'}): the launch "
            "would reject before any host serves",
            fix_hint="lower min_hosts or declare more peers/max_hosts",
        )
    # (d) a LIVE prefix [0, min_hosts) that covers only one half of a
    # split-role fleet: latent peers are excluded from placement until
    # they join, so the lonely live half either rejects at FleetHost
    # construction (decode with no live prefill) or silently defers
    # every filled sequence forever (prefill with no live decode).
    # Statically decidable with explicit peers, or with role auto's
    # rank-split (ranks below prefill_hosts prefill, the rest decode).
    live_prefix: list[str] | None = None
    if fleet.min_hosts:
        if fleet.peers and fleet.min_hosts <= len(fleet.peers):
            live_prefix = [
                p.role for p in fleet.peers[: fleet.min_hosts]
            ]
        elif not fleet.peers and fleet.role == "auto":
            np_hosts = max(1, fleet.prefill_hosts)
            live_prefix = [
                "prefill" if k < np_hosts else "decode"
                for k in range(fleet.min_hosts)
            ]
    if live_prefix is not None:
        live = set(live_prefix)
        for lonely, need in (
            ("prefill", {"decode", "unified"}),
            ("decode", {"prefill", "unified"}),
        ):
            if lonely in live and not live & need:
                col.emit(
                    FLT001,
                    path,
                    f"fleet live prefix [0, min_hosts={fleet.min_hosts}) "
                    f"is {lonely}-only — the "
                    f"{'/'.join(sorted(need))} half is entirely LATENT "
                    "(excluded from placement until it joins), so the "
                    "fleet launches but cannot serve a single stream "
                    "until a join happens",
                    fix_hint="raise min_hosts to cover both roles, or "
                    "reorder peers so the live prefix is "
                    "self-sufficient",
                )
    peer_roles = [p.role for p in (fleet.peers or [])]
    if peer_roles:
        topo_roles = set(peer_roles)
    elif fleet.role in ("prefill", "decode", "unified"):
        topo_roles = {fleet.role}
    else:
        topo_roles = None  # auto rank-split: both halves, count unknown
    runs_prefill = (
        topo_roles is None or topo_roles & {"prefill", "unified"}
    )
    srv = getattr(model_cfg, "serving", None)
    if runs_prefill and srv is not None and srv.kv_blocks > 0:
        window = _declared_window(model_cfg)
        block_len = max(1, srv.kv_block_len)
        need = -(-window // block_len) + 1 if window else 0
        if window and srv.kv_blocks < need:
            col.emit(
                FLT001,
                path,
                f"fleet prefill host with kv_blocks {srv.kv_blocks} < "
                f"{need} needed to admit one max-length prompt "
                f"({window} positions / kv_block_len {block_len} + the "
                "reserved trash block): every admission would raise "
                "before a single prefill chunk ran",
                fix_hint=f"set kv_blocks >= {need} (or 0 for "
                "dense-equivalent sizing)",
            )
    if topo_roles is None:
        return
    if "decode" in topo_roles and not topo_roles & {"prefill", "unified"}:
        col.emit(
            FLT001,
            path,
            "fleet decode host(s) with no prefill-capable peer (no "
            "topology entry of role prefill/unified): nothing can "
            "ever fill their KV blocks — FleetHost rejects this "
            "config at construction",
            fix_hint="add a peers { name: ... role: prefill } entry, "
            "or run role: unified",
        )
    if "prefill" in topo_roles and not topo_roles & {"decode", "unified"}:
        col.emit(
            FLT001,
            path,
            "fleet prefill host(s) with no decode-capable peer (no "
            "topology entry of role decode/unified): filled sequences "
            "would have nowhere to stream — FleetHost rejects this "
            "config at construction",
            fix_hint="add a peers { name: ... role: decode } entry, "
            "or run role: unified",
        )


def rollout_rules(
    model_cfg: ModelConfig, path: str, col: Collector
) -> None:
    """ROL001 — static mirrors of the live-rollout controller's launch
    rejections and its two config-only failure modes
    (serve/rollout.py). A ``fleet { rollout {} }`` block counts as
    CONFIGURED once any of version / checkpoint / canary is set; an
    all-defaults block is inert and skipped. Arms, reported
    independently:

    (a) configured without a ``checkpoint``: the controller has no
        next-version weights to ship and rejects at launch.
    (b) a ``canary`` that is not a declared peer (the controller
        rejects at construction), or one whose declared role is
        ``prefill``: parity probes ride the real serving path, and a
        prefill host's decode phase is gated off — its probe streams
        can NEVER finish, so the canary "fails" by timeout every time,
        a pure config bug that reads like a bad rollout.
    (c) a ``canary`` named in a single-host fleet: the canary IS the
        whole fleet, so a parity mismatch has no un-flipped host to
        keep serving during the rollback window.
    (d) degenerate knobs that disable the health gate instead of
        tuning it (zero probes, zero probe budget, non-positive
        stage-ack window, negative retry budget).

    The dual-resident HBM arm (staged params double the weight
    footprint for the stage window) lives in the cost model
    (lint/cost_model.py), where the per-device bytes are computed."""
    fleet = getattr(model_cfg, "fleet", None)
    if fleet is None:
        return
    ro = getattr(fleet, "rollout", None)
    if ro is None:
        return
    if not (ro.version or ro.checkpoint or ro.canary):
        return
    if not ro.checkpoint:
        col.emit(
            ROL001,
            path,
            "fleet rollout declared (version/canary set) without a "
            "checkpoint — the controller has no next-version weights "
            "to ship and rejects at launch",
            fix_hint='set rollout { checkpoint: "<npz save | sharded '
            'dir | retention folder>" }',
        )
    peers = fleet.peers or []
    roles = {p.name: p.role for p in peers}
    if ro.canary and peers:
        if ro.canary not in roles:
            col.emit(
                ROL001,
                path,
                f"rollout canary {ro.canary!r} is not a declared "
                f"peers entry ({', '.join(sorted(roles))}) — the "
                "controller rejects at construction",
                fix_hint="name an existing peers entry (or omit "
                "canary to take the first decode-capable host)",
            )
        elif roles[ro.canary] == "prefill":
            col.emit(
                ROL001,
                path,
                f"rollout canary {ro.canary!r} has role prefill — its "
                "decode phase is gated off, so parity probe streams "
                "can never finish: every canary would 'fail' by probe "
                "timeout, a config bug that reads like a bad rollout",
                fix_hint="pick a decode/unified peer as the canary",
            )
    n_declared = len(peers) or (fleet.max_hosts or 0)
    if ro.canary and n_declared == 1:
        col.emit(
            ROL001,
            path,
            f"rollout canary {ro.canary!r} named in a single-host "
            "fleet — the canary IS the whole fleet, so a parity "
            "mismatch leaves no un-flipped host serving during the "
            "rollback window",
            fix_hint="drop the canary (single-host rollouts flip "
            "in place) or declare more hosts",
        )
    for knob, val, lo in (
        ("parity_probes", ro.parity_probes, 1),
        ("probe_tokens", ro.probe_tokens, 1),
        ("ship_retries", ro.ship_retries, 0),
    ):
        if val < lo:
            col.emit(
                ROL001,
                path,
                f"rollout {knob} {val} < {lo} — the health gate "
                "cannot run with a degenerate budget",
                fix_hint=f"set rollout {{ {knob}: >= {lo} }} (or omit "
                "for the default)",
            )
    if ro.stage_timeout_s <= 0:
        col.emit(
            ROL001,
            path,
            f"rollout stage_timeout_s {ro.stage_timeout_s:g} <= 0 — a "
            "zero stage-ack window reads every healthy host as a "
            "swap_die pause",
            fix_hint="set rollout { stage_timeout_s: > 0 } (or omit "
            "for the default)",
        )


def wire_rules(model_cfg: ModelConfig, path: str, col: Collector) -> None:
    """WIR001 — static mirrors of the socket transport's launch
    rejections and its one silent-degradation mode (comm/wire.py,
    selected by ``fleet { transport: socket }``). Arms, reported
    independently:

    (a) addressing the factory rejects at launch
        (serve/fleet/host._build_transport): no ``peers`` entries at
        all (a socket fleet has no runtime discovery — the address map
        IS the topology), a peers entry with an empty ``address``, two
        entries binding the SAME address (the second register's bind
        raises mid-launch, after the first host is already up), and a
        missing ``wire.frontdoor_address`` (the router/driver endpoint
        cannot be auto-bound across OS processes).
    (b) wire knobs that disable the retry machinery instead of tuning
        it: non-positive connect/send timeouts or backoff_s (a zero
        deadline times out every frame; a zero backoff is the hot
        reconnect loop the transport exists to prevent), negative
        max_retries.
    (c) a send deadline that cannot cover ONE max-size migration
        message: a retry re-sends the whole frame from scratch, so if
        ``send_timeout_s`` < the bulk npz migration's transfer time at
        the declared ``wire.link_bandwidth_bytes_per_s``, EVERY attempt
        times out mid-frame and the retry budget burns to a false
        peer-death tombstone — the one failure mode that looks like a
        network fault but is pure configuration. Skipped when the
        window/geometry is not statically decidable (SRV001's
        convention) or link_bandwidth_bytes_per_s is 0 (unset)."""
    fleet = getattr(model_cfg, "fleet", None)
    if fleet is None or fleet.transport != "socket":
        return
    wire = fleet.wire
    peers = fleet.peers or []
    if not peers:
        col.emit(
            WIR001,
            path,
            "transport: socket with no peers entries — the address map "
            "IS the topology (no runtime discovery), so the launch "
            "rejects before any host binds",
            fix_hint="declare every host as peers { name: ... role: "
            "... address: \"host:port\" }",
        )
    unaddressed = [p.name for p in peers if not p.address]
    if unaddressed:
        col.emit(
            WIR001,
            path,
            f"transport: socket peers without an address: "
            f"{', '.join(unaddressed)} — the launch rejects before any "
            "host binds (mailbox infers endpoints from the shared "
            "root; sockets cannot)",
            fix_hint="give every peers entry address: \"host:port\"",
        )
    seen_addr: dict[str, str] = {}
    frontdoor = wire.frontdoor_address if wire is not None else ""
    if frontdoor:
        seen_addr[frontdoor] = "wire.frontdoor_address"
    for p in peers:
        if not p.address:
            continue
        if p.address in seen_addr:
            col.emit(
                WIR001,
                path,
                f"peers entry {p.name!r} binds address {p.address!r} "
                f"already claimed by {seen_addr[p.address]} — the "
                "second register's bind raises mid-launch, after the "
                "first host is already up",
                fix_hint="give every endpoint a distinct host:port",
            )
        else:
            seen_addr[p.address] = f"peers entry {p.name!r}"
    if peers and not frontdoor:
        col.emit(
            WIR001,
            path,
            "transport: socket without wire.frontdoor_address — the "
            "front-door router/driver endpoint cannot be auto-bound "
            "across OS processes, so hosts cannot return results or "
            "hand back drained sequences",
            fix_hint='add wire { frontdoor_address: "host:port" }',
        )
    if wire is None:
        return
    for knob, val in (
        ("connect_timeout_s", wire.connect_timeout_s),
        ("send_timeout_s", wire.send_timeout_s),
        ("backoff_s", wire.backoff_s),
    ):
        if val is not None and val <= 0:
            col.emit(
                WIR001,
                path,
                f"wire.{knob} {val:g} <= 0 — a zero deadline times out "
                "every frame and a zero backoff is the hot reconnect "
                "loop the transport exists to prevent",
                fix_hint=f"set wire.{knob} > 0 (or omit for the "
                "default)",
            )
    if wire.max_retries is not None and wire.max_retries < 0:
        col.emit(
            WIR001,
            path,
            f"wire.max_retries {wire.max_retries} < 0 — the retry "
            "budget cannot be negative (0 means single-attempt)",
            fix_hint="set wire.max_retries >= 0 (or omit for the "
            "default)",
        )
    # (c) the deadline-vs-migration-size budget. A migration frame is
    # the sequence's whole serving state as ONE bulk message (gather,
    # wire, scatter): K and V per attention layer across every block a
    # max-length sequence touches, plus its token lane — sized from the
    # same declared geometry kernel_rules reads
    bw = wire.link_bandwidth_bytes_per_s
    timeout = wire.send_timeout_s
    srv = getattr(model_cfg, "serving", None)
    if not bw or bw <= 0 or not timeout or timeout <= 0 or srv is None:
        return
    from .cost_model import _attention_geometry  # lazy: cost_model
    # imports _declared_window from this module

    n_layers, heads, head_dim = _attention_geometry(model_cfg)
    window = _declared_window(model_cfg)
    if not (n_layers and heads and head_dim and window):
        return  # geometry not statically decidable: nothing to budget
    block_len = max(1, srv.kv_block_len)
    n_blocks = -(-window // block_len)
    msg_bytes = (
        2 * n_layers * heads * n_blocks * block_len * head_dim * 4
        + window * 4  # token lane (i32)
        + 4096  # npz/header overhead
    )
    need_s = msg_bytes / bw
    if timeout < need_s:
        col.emit(
            WIR001,
            path,
            f"wire.send_timeout_s {timeout:g} cannot cover one "
            f"max-size migration message: ~{msg_bytes} bytes "
            f"({n_layers} layers x {heads} heads x {n_blocks} blocks "
            f"x {block_len} x {head_dim} K+V f32, window {window}) at "
            f"link_bandwidth_bytes_per_s {bw:g} needs ~{need_s:.2f}s "
            "per attempt — retries re-send from scratch, so every "
            "attempt times out mid-frame and the budget burns to a "
            "false peer-death tombstone",
            fix_hint=f"set wire.send_timeout_s >= {need_s:.2f} or "
            "declare the real link bandwidth",
        )
    # (d) the same budget for the fleet prefix cache's cache_ship
    # frame: a max-depth ship carries every block of a max-length
    # prompt's K/V (no token lane — digests ride in the JSON header).
    # A too-short deadline here is WORSE than a failed migration: the
    # requester holds the request until its fetch deadline, then
    # degrades to plain prefill — every warm admission pays the fetch
    # timeout and the cache never helps. Gated on the prefix cache
    # actually being on (no cache, no ship frames)
    pc = getattr(srv, "prefix_cache", None)
    if pc is None or not getattr(pc, "enabled", False):
        return
    ship_bytes = (
        2 * n_layers * heads * n_blocks * block_len * head_dim * 4
        + n_blocks * 32  # hex digest chain in the JSON header
        + 4096  # npz/header overhead
    )
    ship_need_s = ship_bytes / bw
    if timeout < ship_need_s:
        col.emit(
            WIR001,
            path,
            f"wire.send_timeout_s {timeout:g} cannot cover one "
            f"max-prefix cache_ship frame: ~{ship_bytes} bytes "
            f"({n_layers} layers x {heads} heads x {n_blocks} blocks "
            f"x {block_len} x {head_dim} K+V f32) at "
            f"link_bandwidth_bytes_per_s {bw:g} needs "
            f"~{ship_need_s:.2f}s per attempt — every cross-host "
            "prefix fetch would burn its deadline and degrade to "
            "plain prefill, so the fleet cache never helps",
            fix_hint=f"set wire.send_timeout_s >= {ship_need_s:.2f}, "
            "declare the real link bandwidth, or disable "
            "serving.prefix_cache",
        )


def elastic_rules(
    model_cfg: ModelConfig,
    widths: dict[str, int] | None,
    path: str,
    col: Collector,
) -> None:
    """ELA001 — static mirror of the elastic-restore admission check
    (resilience/reshard.py ``check_manifest``; threaded through
    ``--cluster`` like SRV001/KRN002). When the conf's ``checkpoint``
    field names a SHARDED checkpoint dir whose manifest is readable,
    every saved entry's recorded PartitionSpec must be hostable by the
    target cluster's mesh: a spec naming an axis the mesh vocabulary
    lacks (a foreign manifest), or a dim with fewer elements than the
    named axes' combined target width wants shards (beyond even the
    pad/replicate fallback), rejects at restore time — after the pod
    is already up. The SAME ``hostable`` predicate runs here, so lint
    and runtime can never disagree. A checkpoint path that does not
    exist (yet) or is an npz file is skipped: only a present, parseable
    manifest is statically decidable, like SRV001's window."""
    import json
    import os

    if widths is None:
        return
    ckpt = getattr(model_cfg, "checkpoint", None)
    if not ckpt or not os.path.isdir(ckpt):
        return
    try:
        with open(os.path.join(ckpt, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return  # not a readable sharded manifest: nothing decidable
    if manifest.get("format") != "singa-tpu-sharded-v1":
        # the runtime never feeds a foreign-format manifest to the
        # resharder (ShardedCheckpoint rejects it first) — lint must
        # not claim a reshard verdict the runtime would never reach
        return
    from ..resilience.reshard import check_manifest

    problems = check_manifest(manifest, widths)
    # one diagnostic per distinct reason, naming one exemplar entry —
    # a 200-param model sharing one bad axis is ONE problem
    by_reason: dict[str, str] = {}
    for key in sorted(problems):
        by_reason.setdefault(problems[key], key)
    for reason, key in by_reason.items():
        more = sum(1 for r in problems.values() if r == reason) - 1
        extra = f" (+{more} more entr{'y' if more == 1 else 'ies'})" \
            if more else ""
        col.emit(
            ELA001,
            path,
            f"checkpoint {ckpt!r} entry {key!r}{extra}: {reason} — "
            "the elastic restore would reject this resume at runtime "
            "(resilience/reshard.py)",
            fix_hint="resume on a mesh whose axis widths can host the "
            "manifest's specs, or point `checkpoint` at a compatible "
            "save",
        )


def kernel_rules(model_cfg: ModelConfig, path: str, col: Collector) -> None:
    """KRN001 — static mirror of the serving engine's fused-kernel
    geometry rejection (serve/engine.py consults the SAME
    ops.paged_attention.fusable predicate at construction). A conf
    that selects ``kernels { paged_attention: fused, interpret: false }``
    with a ``kv_block_len`` or head_dim the compiled (Mosaic) kernel
    cannot tile would reject at engine build time, after pod time is
    already burned; flag it at lint time instead. Interpret mode tiles
    anything, so ``interpret: true`` (the default) never fires. The
    head_dim comes from the conf's declared dims — the kEmbedding
    layer's ``embedding_dim`` over the kAttention layer's
    ``num_heads`` — and is skipped when either is undeclared (not
    statically decidable, like SRV001's window)."""
    kern = getattr(model_cfg, "kernels", None)
    if kern is None or kern.paged_attention != "fused" or kern.interpret:
        return
    from ..ops.paged_attention import fusable

    srv = getattr(model_cfg, "serving", None)
    block_len = srv.kv_block_len if srv is not None else (
        schema.ServingConfig.FIELDS["kv_block_len"].default
    )
    head_dim = 0
    net_cfg = model_cfg.neuralnet
    if net_cfg is not None:
        dim = max(
            (
                l.embedding_param.embedding_dim
                for l in net_cfg.layer
                if l.embedding_param is not None
            ),
            default=0,
        )
        heads = max(
            (
                l.attention_param.num_heads
                for l in net_cfg.layer
                if l.attention_param is not None
            ),
            default=0,
        )
        if dim and heads and dim % heads == 0:
            head_dim = dim // heads
    # check each declared dimension independently (a missing head_dim
    # must not mask an untileable block_len and vice versa), but dedupe
    # dimension-independent reasons — a missing pallas install is ONE
    # problem, not one per probed dim
    reasons = dict.fromkeys(
        r
        for r in (
            fusable(block_len, 128, interpret=False),
            fusable(8, head_dim, interpret=False) if head_dim else None,
        )
        if r is not None
    )
    for reason in reasons:
        col.emit(
            KRN001,
            path,
            f"kernels.paged_attention 'fused' with interpret off, but "
            f"{reason} — the engine will reject this config at "
            "construction",
            fix_hint="pick a tileable geometry (kv_block_len % 8 == 0, "
            "head_dim % 128 == 0), or set kernels { interpret: true }, "
            "or keep paged_attention: reference",
        )


def ring_rules(
    model_cfg: ModelConfig,
    cluster_cfg: ClusterConfig | None,
    widths: dict[str, int] | None,
    path: str,
    col: Collector,
) -> None:
    """KRN002 — static mirror of the quantized-ring rejections (the
    trainer consults the SAME ``ring_reducible`` predicate and the same
    quantized-block requirement at construction;
    ops/quantized_collective.py). Seven arms, each reported
    independently: (1) ``kernels { grad_allreduce: quantized_ring }``
    without an active ``grad_comm { mode: quantized }`` block — the
    ring is the quantized collective's wire implementation, there is
    nothing to put on the wire; (2) combined with the replica (async
    PS) engine, whose EASGD protocol owns its own sync math — the
    CMM001 static mirror for this site, threaded through ``--cluster``;
    (3) the CD engine — its layerwise step does not take the ring's
    data-axis shard_map shape (``CDTrainer`` rejects at construction);
    (4) a batch-stat (kBatchNorm) net — inside the ring's per-shard
    backward, sync BN's GSPMD-psum'd global moments would silently
    become local-shard stats; (5) a >1-wide non-data mesh axis under
    the FLAT ring — q8_hier with a covering ring {} factorization is
    the acceptance path; (5b, q8_hier only) a broken two-level
    geometry — ``hier_ring_geometry``'s reason verbatim (missing
    ``ring {}`` block, an intra/inter axis naming no mesh axis — with
    a did-you-mean over the cluster's axes — an intra_degree the data
    width cannot divide, or an uncovered >1-wide leftover axis),
    threaded through ``--cluster``; (6) a train batchsize the
    reduction width (K*M for q8_hier) cannot divide — each shard
    computes its own local partial; (7) a reduction width the ring's
    bucket chunking cannot divide — checked on the
    statically-declared neuron dims (a layer's bias gradient is
    ``(num_output,)``, chunked on dim 0; weight input dims need shape
    inference and are left to the runtime predicate)."""
    kern = getattr(model_cfg, "kernels", None)
    if kern is None or kern.grad_allreduce not in (
        "quantized_ring", "q8_hier"
    ):
        return
    impl = kern.grad_allreduce
    hier = impl == "q8_hier"
    gc = getattr(model_cfg, "grad_comm", None)
    if gc is None or gc.mode != "quantized":
        col.emit(
            KRN002,
            path,
            f"kernels.grad_allreduce '{impl}' without an active "
            "grad_comm { mode: quantized } block: the ring is the "
            "quantized collective's wire implementation — the trainer "
            "rejects this config at construction",
            fix_hint="add grad_comm { mode: quantized dtype: int8 }, or "
            "keep grad_allreduce: reference",
        )
    if (
        cluster_cfg is not None
        and cluster_cfg.nservers > 0
        and not cluster_cfg.synchronous
        and model_cfg.alg != "kContrastiveDivergence"
        and model_cfg.updater is not None
    ):
        col.emit(
            KRN002,
            path,
            f"kernels.grad_allreduce '{impl}' with an "
            "asynchronous nservers>0 cluster: the replica engine's "
            "EASGD protocol owns its own gradient sync and rejects the "
            "ring at construction",
            fix_hint="drop the kernels/grad_comm blocks, or run the "
            "synchronous engine (synchronous: true / nservers: 0)",
        )
    if model_cfg.alg == "kContrastiveDivergence":
        col.emit(
            KRN002,
            path,
            f"kernels.grad_allreduce '{impl}' with the "
            "kContrastiveDivergence engine: the CD trainer's layerwise "
            "step does not take the ring's data-axis shard_map shape "
            "and rejects it at construction",
            fix_hint="keep grad_allreduce: reference for CD jobs",
        )
    bn = [
        l.name
        for l in (model_cfg.neuralnet.layer if model_cfg.neuralnet else [])
        if l.type == "kBatchNorm"
    ]
    if bn:
        col.emit(
            KRN002,
            path,
            f"kernels.grad_allreduce '{impl}' with batch-stat "
            f"layers {bn}: the ring's per-shard backward would turn "
            "sync BatchNorm into local-shard BN (biased variance) — "
            "the trainer rejects this config at construction",
            fix_hint="drop the kBatchNorm layers, or keep "
            "grad_allreduce: reference",
        )
    ring_cfg = getattr(model_cfg, "ring", None)
    ndata = (widths or {}).get("data", 0)
    if hier:
        from ..ops.quantized_collective import hier_ring_geometry

        if widths is not None:
            geom = hier_ring_geometry(widths, ring_cfg)
        else:
            # no --cluster: validate the ring {} block's FORM only,
            # against a mesh that cannot trigger width errors
            intra = getattr(ring_cfg, "intra_axis", "") or ""
            inter = getattr(ring_cfg, "inter_axis", "") or ""
            deg = int(getattr(ring_cfg, "intra_degree", 0) or 0)
            fake = {a: 1 for a in (intra, inter) if a}
            fake.setdefault("data", max(1, deg))
            geom = hier_ring_geometry(fake, ring_cfg)
        if isinstance(geom, str):
            hint = (
                "declare ring { intra_degree } dividing the data "
                "width, or intra_axis/inter_axis naming two real mesh "
                "axes that cover every >1-wide axis"
            )
            if widths and ring_cfg is not None:
                import difflib

                sugg = []
                for role in ("intra_axis", "inter_axis"):
                    ax = getattr(ring_cfg, role, "")
                    if ax and ax not in widths:
                        close = difflib.get_close_matches(
                            ax, sorted(widths), n=1
                        )
                        if close:
                            sugg.append(f"{role}: {close[0]}")
                if sugg:
                    hint = "did you mean " + ", ".join(sugg) + "?"
            col.emit(
                KRN002,
                path,
                f"kernels.grad_allreduce 'q8_hier' cannot run: {geom} "
                "— the trainer rejects this config at construction",
                fix_hint=hint,
            )
        else:
            ndata = geom[2] * geom[3]
            if geom[0] != geom[1] and bool(model_cfg.zero_update):
                col.emit(
                    KRN002,
                    path,
                    "kernels.grad_allreduce 'q8_hier' with named "
                    "intra_axis/inter_axis does not compose with "
                    "zero_update (the update layout shards over the "
                    "data axis only) — the trainer rejects this "
                    "config at construction",
                    fix_hint="use the factored ring { intra_degree } "
                    "form, or drop zero_update",
                )
    else:
        other = {
            a: w
            for a, w in (widths or {}).items()
            if a != "data" and w > 1
        }
        if other:
            col.emit(
                KRN002,
                path,
                "kernels.grad_allreduce 'quantized_ring' runs over the "
                f"data axis only, but the cluster also shards {other} "
                "— the trainer rejects this config at construction",
                fix_hint="switch to grad_allreduce: q8_hier with a "
                "ring { intra_axis/inter_axis } block covering the "
                "extra axis, widen only the data axis, or keep "
                "grad_allreduce: reference",
            )
    net_cfg = model_cfg.neuralnet
    if ndata <= 1 or net_cfg is None:
        return
    for l in net_cfg.layer:
        dp = getattr(l, "data_param", None)
        bs = getattr(dp, "batchsize", 0) if dp is not None else 0
        if bs and "kTrain" not in (l.exclude or []) and bs % ndata:
            col.emit(
                KRN002,
                path,
                f"kernels.grad_allreduce '{impl}' on a {ndata}"
                "-wide data reduction, but layer "
                f"{l.name!r}'s train batchsize {bs} is not divisible "
                "by it: each shard computes its own local partial "
                "gradients — the trainer rejects this config at "
                "construction",
                fix_hint=f"pick a batchsize divisible by {ndata}, or "
                "resize the data axis",
            )
    from ..ops.quantized_collective import ring_reducible

    shapes = {}
    for l in net_cfg.layer:
        fields = _NEURON_DIM_FIELDS.get(l.type)
        if fields:
            sub = getattr(l, fields[0], None)
            dim = getattr(sub, fields[1], None) if sub else None
            if dim:
                shapes[f"{l.name} ({fields[1]} {dim})"] = (dim,)
    reason = ring_reducible(shapes, ndata)
    if reason is not None:
        col.emit(
            KRN002,
            path,
            f"kernels.grad_allreduce '{impl}' on a {ndata}-wide "
            f"data reduction, but {reason} — the trainer rejects this "
            "config at construction",
            fix_hint=f"pick neuron dims divisible by {ndata}, resize "
            "the data axis, or keep grad_allreduce: reference",
        )


# ---------------------------------------------------------------------------
# sharding rules (model conf x cluster axis widths)
# ---------------------------------------------------------------------------

#: config-declared neuron-dim per layer type, for the static SHD001
#: fallback when the net can't be built (data sources absent). The
#: build-based check in shape_rules covers every param precisely — and
#: ring_rules reuses the table for KRN002's bias-gradient chunk check.
_NEURON_DIM_FIELDS = {
    "kInnerProduct": ("inner_product_param", "num_output"),
    "kDense": ("dense_param", "num_output"),
    "kConvolution": ("convolution_param", "num_filters"),
    "kRBM": ("rbm_param", "num_hidden"),
}


def sharding_rules_static(
    model_cfg: ModelConfig,
    widths: dict[str, int],
    path: str,
    col: Collector,
    *,
    neuron_dims: bool = True,
) -> None:
    """SHD001/SHD003 from config fields alone (no data, no layer setup).

    Mirrors parallel/shardings._param_layout's divisibility condition: a
    kLayerPartition layer whose neuron dim is not a multiple of the model
    axis gets padded storage (experts: replication) instead of an even
    shard — legal, but a silent perf/memory cliff worth a warning.

    ``neuron_dims=False`` keeps only the SHD003 batch check — used when
    the net built and _sharding_rules_built already covered every param
    precisely (the config-level SHD001 heuristic would double-report).
    """
    net_cfg = model_cfg.neuralnet
    if net_cfg is None:
        return
    nmodel = widths.get("model", 1)
    ndata = widths.get("data", 1)
    for l in net_cfg.layer:
        ptype = l.partition_type or net_cfg.partition_type
        if neuron_dims and nmodel > 1 and ptype == "kLayerPartition":
            fields = _NEURON_DIM_FIELDS.get(l.type)
            if fields:
                sub = getattr(l, fields[0], None)
                dim = getattr(sub, fields[1], None) if sub else None
                if dim and dim % nmodel:
                    col.emit(
                        SHD001,
                        f"{path} (layer {l.name!r})",
                        f"neuron dim {dim} ({fields[1]}) not divisible by "
                        f"model axis {nmodel}: storage pads to "
                        f"{dim + (-dim % nmodel)} rather than sharding "
                        "evenly",
                        fix_hint=f"pick a multiple of {nmodel} or widen "
                        "the data axis instead",
                    )
        if ndata > 1 and l.data_param is not None and l.data_param.batchsize:
            bs = l.data_param.batchsize
            if bs % ndata:
                col.emit(
                    SHD003,
                    f"{path} (layer {l.name!r})",
                    f"batchsize {bs} not divisible by data axis {ndata}",
                    fix_hint=f"use a multiple of {ndata}",
                )


def _locs_of(
    text: str | None,
) -> dict[str, list[textproto.FieldLoc]] | None:
    """The span tree for ``text``, or None when it cannot be lexed (the
    caller already reported the parse failure — spans are best-effort)."""
    if not text:
        return None
    try:
        _, locs = textproto.parse_with_locs(text)
    except textproto.TextProtoError:
        return None
    return locs


_UNKNOWN_FIELD = re.compile(r"unknown field '([^']+)'")
_BAD_ENUM = re.compile(r"field '[^']+': ('[^']+') not in enum")


def _walk_explains(err_msg: str, walk_diags: list) -> bool:
    """Whether the strict parser's ConfigError re-states a problem the raw
    walk already reported. The walk validates field names (CFG001), enum
    membership (CFG002), scalar coercion and required fields (CFG000, with
    the strict parser's exact message text); only a strict-parse failure
    matching none of those is new information. Matching is per-problem,
    never "the walk found *something*" — the strict parse stops at its
    first error, so suppressing on unrelated findings would hide it."""
    m = _UNKNOWN_FIELD.search(err_msg)
    if m:
        needle = f"unknown field '{m.group(1)}'"
        return any(
            d.code == "CFG001" and needle in d.msg for d in walk_diags
        )
    m = _BAD_ENUM.search(err_msg)
    if m:
        needle = f"{m.group(1)} not in"
        return any(
            d.code == "CFG002" and needle in d.msg for d in walk_diags
        )
    return any(d.msg == err_msg for d in walk_diags)


def lint_model_text(
    text: str,
    path: str,
    col: Collector,
    *,
    widths: dict[str, int] | None = None,
    raw: dict[str, list[Any]] | None = None,
) -> ModelConfig | None:
    """Full static pass over one model conf: raw walk, strict parse,
    graph rules, static sharding rules. Returns the parsed config when it
    parsed (the shape pass builds on it), else None. Pass ``raw`` when
    the caller already parsed the text (the CLI does, to classify
    model vs cluster confs)."""
    if raw is None:
        try:
            raw = textproto.parse(text)
        except textproto.TextProtoError as e:
            col.emit(CFG000, path, str(e))
            return None
    before = len(col.diagnostics)
    walk_raw_config(
        raw, ModelConfig, path, col, text=text, locs=_locs_of(text)
    )
    try:
        model_cfg = ModelConfig.from_fields(raw)
    except ConfigError as e:
        if not _walk_explains(str(e), col.diagnostics[before:]):
            col.emit(CFG000, path, str(e))
        return None
    graph_rules(model_cfg, path, col)
    serving_rules(model_cfg, path, col)
    fleet_rules(model_cfg, path, col)
    rollout_rules(model_cfg, path, col)
    wire_rules(model_cfg, path, col)
    kernel_rules(model_cfg, path, col)
    if widths:
        sharding_rules_static(model_cfg, widths, path, col)
    return model_cfg


def lint_cluster_text(
    text: str,
    path: str,
    col: Collector,
    *,
    raw: dict[str, list[Any]] | None = None,
) -> tuple[ClusterConfig | None, dict[str, int] | None]:
    """Static pass over one cluster conf; returns (config, axis widths)."""
    if raw is None:
        try:
            raw = textproto.parse(text)
        except textproto.TextProtoError as e:
            col.emit(CFG000, path, str(e))
            return None, None
    before = len(col.diagnostics)
    walk_raw_config(
        raw, ClusterConfig, path, col, text=text, locs=_locs_of(text)
    )
    try:
        cluster_cfg = ClusterConfig.from_fields(raw)
    except ConfigError as e:
        if not _walk_explains(str(e), col.diagnostics[before:]):
            col.emit(CFG000, path, str(e))
        return None, None
    return cluster_cfg, cluster_rules(cluster_cfg, path, col)
