"""Cost-aware shardlint: static per-config HBM / collective / bubble model.

The reference's parameter-server era had no way to know a model/cluster
config was infeasible until workers OOM'd or the server saturated
(src/server/server.cc); this pass answers the capacity question BEFORE
any pod time is burned. From the parsed model conf + cluster conf +
sharding plan it models:

  (a) the per-device HBM footprint — fp32 master params (stored, padded
      shapes, divided by their forward sharding), updater slots in the
      ``zero_update`` UPDATE layout (the same dim-selection rule as
      parallel/shardings.zero_update_shardings), error-feedback
      residuals, the activation working set per microbatch, and the
      serving tier's paged KV pool;
  (b) the collective bytes each device moves per step — the data-axis
      gradient reduction (fp32 ring all-reduce, reduce-scatter alone
      under zero_update, or the quantized ring's int8-on-the-wire
      ppermutes via ops/quantized_collective's analytic model), the
      ZeRO param allgather, MoE all-to-all capacity buffers, and
      pipeline edge sends;
  (c) the GPipe fill/drain bubble fraction from stage count x
      microbatches.

Rules (threaded through ``tools/lint.py --cluster`` like SRV001/KRN002):

  MEM001  ERROR  predicted per-device bytes exceed the cluster's declared
                 ``device_hbm_bytes`` budget (0 = no budget, silent)
  COST001 WARN   modeled collective bytes exceed a configurable fraction
                 of modeled compute bytes (``--cost-comm-fraction``)
  SRV002  WARN   KV-pool byte sizing + slots x block-budget admission
                 feasibility (SRV001's capacity sibling)
  FLT002  WARN   per-role fleet capacity below the declared offered load
                 (``fleet { load { ... } }``)

``tools/lint.py --explain-cost`` renders the full report table.

Parity bar (tests/test_cost_model.py, CI-held): the modeled opt-state
bytes equal the dryrun trainer's measured ``opt_state_bytes_per_device``
and the modeled ring wire bytes equal BOTH ``modeled_wire_bytes_per_step``
and the jaxpr-counted ppermute bytes — a cost model that drifts from the
real program is a lint bug. Under ``grad_allreduce: q8_hier`` the single
ring row splits into intra-slice (f32) and inter-slice (quantized) rows,
each parity-held against ``modeled_wire_bytes_levels``; a declared
``cluster { inter_slice_bandwidth }`` adds a DCN transfer-time row to
``--explain-cost``.

Like shape_rules, the HBM/collective half needs a BUILT net (data layers
open their sources); when the shards aren't present the model degrades
silently — the config-only arms (SRV002 sizing, FLT002 load) still run.
"""

from __future__ import annotations

import dataclasses
import math

from ..config import schema
from ..config.schema import ClusterConfig, ModelConfig
from .core import Collector, ERROR, WARNING, rule
from .net_rules import _declared_window

MEM001 = rule(
    "MEM001",
    ERROR,
    "predicted per-device HBM bytes exceed the declared device_hbm_bytes",
)
COST001 = rule(
    "COST001",
    WARNING,
    "modeled collective bytes exceed the budgeted fraction of compute",
)
SRV002 = rule(
    "SRV002",
    WARNING,
    "serving KV pool undersized for the declared slot concurrency",
)
FLT002 = rule(
    "FLT002",
    WARNING,
    "fleet role capacity below the declared offered load",
)

#: COST001's default comm/compute budget (overridable per run via
#: ``tools/lint.py --cost-comm-fraction``)
DEFAULT_COMM_FRACTION = 0.5


@dataclasses.dataclass
class CostReport:
    """The static cost model for one (model conf, cluster conf) pair.

    All byte figures are PER DEVICE; collectives are per STEP. Component
    naming mirrors the runtime it models: ``opt_bytes`` is the number
    ``trainer.opt_state_bytes_per_device()`` measures, the grad-reduce
    collective row is ``trainer.modeled_wire_bytes_per_step()``."""

    path: str
    widths: dict[str, int]
    nmicro: int
    stages: int
    # --- HBM components (bytes/device) ---
    param_bytes: int
    opt_bytes: int
    residual_bytes: int
    act_bytes: int  # activation working set per microbatch
    kv_bytes: int  # serving KV pool; 0 = none / not statically decidable
    #: per layer (param group): (layer name, n params, bytes/device)
    param_groups: list[tuple[str, int, int]]
    # --- collectives (label, bytes/device/step) ---
    collectives: list[tuple[str, int]]
    compute_bytes: int  # modeled MXU operand traffic per step (proxy)
    bubble: float  # GPipe fill/drain fraction, 0.0 when not pipelined
    notes: list[str]
    #: cluster { inter_slice_bandwidth } (bytes/s DCN); 0 = undeclared
    inter_slice_bandwidth: int = 0

    @property
    def hbm_bytes(self) -> int:
        return (
            self.param_bytes
            + self.opt_bytes
            + self.residual_bytes
            + self.act_bytes
            + self.kv_bytes
        )

    @property
    def collective_bytes(self) -> int:
        return sum(b for _, b in self.collectives)


# ---------------------------------------------------------------------------
# sharding-layout mirrors (pure Python: the lint host has no mesh)
# ---------------------------------------------------------------------------


def _layout(net, widths: dict[str, int]):
    """-> iterator of (layer, name, spec, stored_shape, fwd_divs).

    The pure-Python mirror of parallel/shardings._param_layout:
    ``stored_shape`` is the (possibly pad-to-multiple) storage shape and
    ``fwd_divs[d]`` the mesh-axis width dim ``d`` is sharded over in the
    FORWARD layout (None = replicated on that dim). Kept in lockstep
    with _param_layout — the parity tests hold the composition."""
    nmodel = widths.get("model", 1)
    nexpert = widths.get("expert", 1)
    for layer in net.layers:
        for name, spec in layer.param_specs().items():
            shape = list(spec.shape)
            divs: list[int | None] = [None] * len(shape)
            if (
                layer.partition_dim == 1
                and spec.neuron_axis is not None
                and nmodel > 1
            ):
                d = spec.neuron_axis
                shape[d] += -shape[d] % nmodel
                divs[d] = nmodel
            elif spec.expert_axis is not None and nexpert > 1:
                if spec.shape[spec.expert_axis] % nexpert == 0:
                    divs[spec.expert_axis] = nexpert
                # else: indivisible expert count replicates (SHD001)
            yield layer, name, spec, tuple(shape), divs


def _zero_dim(
    stored: tuple, divs: list, ndata: int
) -> int | None:
    """The dim zero_update lays over the data axis: the FIRST
    still-replicated dim the data width divides evenly (None = the
    replicate fallback) — zero_update_shardings' selection rule."""
    if ndata <= 1:
        return None
    for d, size in enumerate(stored):
        if divs[d] is None and size and size % ndata == 0:
            return d
    return None


def _shard_elems(stored: tuple, divs: list) -> int:
    n = 1
    for size, div in zip(stored, divs):
        n *= size // div if div else size
    return max(n, 1) if stored else 1


def _n_slots(model_cfg: ModelConfig) -> int:
    """Updater slot count (history / history+update) for the configured
    updater type — the multiplier on per-param optimizer bytes."""
    upd = model_cfg.updater
    if upd is None:
        return 0
    from ..optim import _UPDATERS

    cls = _UPDATERS.get(upd.type)
    return len(cls.SLOTS) if cls is not None else 0


def _act_itemsize(model_cfg: ModelConfig) -> int:
    return 2 if model_cfg.compute_dtype in ("bfloat16", "float16") else 4


# ---------------------------------------------------------------------------
# config-only components (no net build required)
# ---------------------------------------------------------------------------


def _attention_geometry(
    model_cfg: ModelConfig,
) -> tuple[int, int, int]:
    """(n_attention_layers, n_heads, head_dim) from declared dims, all 0
    when not statically decidable (kernel_rules' skip convention)."""
    net_cfg = model_cfg.neuralnet
    if net_cfg is None:
        return 0, 0, 0
    n_layers = sum(1 for l in net_cfg.layer if l.attention_param is not None)
    dim = max(
        (
            l.embedding_param.embedding_dim
            for l in net_cfg.layer
            if l.embedding_param is not None
        ),
        default=0,
    )
    heads = max(
        (
            l.attention_param.num_heads
            for l in net_cfg.layer
            if l.attention_param is not None
        ),
        default=0,
    )
    if not (n_layers and dim and heads and dim % heads == 0):
        return n_layers, 0, 0
    return n_layers, heads, dim // heads


def kv_pool_bytes(
    model_cfg: ModelConfig, widths: dict[str, int], notes: list[str]
) -> int:
    """Per-device bytes of the serving engine's paged KV pools: K and V
    per attention layer, each ``(n_blocks, heads, block_len, head_dim)``
    f32 (serve/engine.py), heads sharded over the model axis when it
    divides (serving_kv_shardings). 0 when the conf declares no serving
    block or the geometry is not statically decidable."""
    srv = model_cfg.serving
    if srv is None:
        return 0
    window = _declared_window(model_cfg)
    n_layers, heads, head_dim = _attention_geometry(model_cfg)
    if not window or not head_dim:
        notes.append(
            "serving KV pool not modeled: window or head geometry not "
            "statically declared"
        )
        return 0
    block_len = max(1, srv.kv_block_len)
    per_seq = window // block_len  # KVPool.for_model's floor
    n_blocks = srv.kv_blocks or srv.slots * per_seq + 1
    nmodel = widths.get("model", 1)
    div = nmodel if nmodel > 1 and heads % nmodel == 0 else 1
    return 2 * n_layers * n_blocks * (heads // div) * block_len * head_dim * 4


# ---------------------------------------------------------------------------
# the built-net model
# ---------------------------------------------------------------------------


def _grad_comm_active(model_cfg: ModelConfig) -> bool:
    gc = model_cfg.grad_comm
    return gc is not None and not (gc.mode == "exact" and gc.buckets <= 1)


def _ring_active(model_cfg: ModelConfig) -> bool:
    kern = model_cfg.kernels
    gc = model_cfg.grad_comm
    return (
        kern is not None
        and kern.grad_allreduce in ("quantized_ring", "q8_hier")
        and gc is not None
        and gc.mode == "quantized"
    )


def _hier_geometry(
    model_cfg: ModelConfig, widths: dict[str, int]
) -> tuple[int, int] | None:
    """(K, M) when the hierarchical ring is requested AND its geometry
    resolves on these widths; None for the flat ring or a broken ring{}
    block (KRN002 owns the diagnostic for the latter — the trainer
    rejects that config at construction, so there is no step to price)."""
    kern = model_cfg.kernels
    if kern is None or kern.grad_allreduce != "q8_hier":
        return None
    from ..ops.quantized_collective import hier_ring_geometry

    geom = hier_ring_geometry(widths, model_cfg.ring)
    if isinstance(geom, str):
        return None
    return geom[2], geom[3]


def build_cost_model(
    model_cfg: ModelConfig,
    widths: dict[str, int] | None,
    path: str,
) -> CostReport | None:
    """Build the train net and model its per-device cost, or None when
    the net cannot build (data sources absent — shape_rules' SHP000
    degradation — or a breakage shape_pass already reports)."""
    from ..graph.builder import build_net

    if model_cfg.neuralnet is None:
        return None
    try:
        net = build_net(model_cfg, "kTrain")
    except Exception:
        # OSError: data shards absent (the usual repo-lint case, SHP000).
        # Anything else: shape_pass owns the diagnostic (SHP001).
        return None

    widths = dict(widths or {})
    ndata = max(1, widths.get("data", 1))
    npipe = max(1, widths.get("pipe", 1))
    nexpert = max(1, widths.get("expert", 1))
    notes: list[str] = []

    # --- pipeline staging ------------------------------------------------
    staged_ids = sorted(
        {
            l.cfg.locationid
            for l in net.layers
            if l.cfg.locationid is not None
        }
    )
    stages = npipe if npipe > 1 and len(staged_ids) >= 2 else 1
    nmicro = 1
    if stages > 1:
        nmicro = model_cfg.pipeline_microbatches or stages
    bubble = (stages - 1) / (nmicro + stages - 1) if stages > 1 else 0.0

    # --- params / optimizer slots / residuals ----------------------------
    zero = bool(model_cfg.zero_update)
    nslots = _n_slots(model_cfg)
    gc = model_cfg.grad_comm
    residuals = (
        gc is not None and gc.mode == "quantized" and gc.error_feedback
    )
    ring = _ring_active(model_cfg)
    hier = _hier_geometry(model_cfg, widths) if ring else None
    if hier is not None:
        # the two-level ring reduces over intra*inter devices; the
        # named-axes form widens the data reduction past widths["data"]
        ndata = max(ndata, hier[0] * hier[1])

    param_bytes = 0
    opt_bytes = 0
    residual_bytes = 0
    groups: dict[str, tuple[int, int]] = {}
    zero_gather_bytes = 0  # stored bytes moved by the ZeRO param allgather
    gather: dict[str, bool] = {}  # ring allgather-phase map, per spec name
    sizes: dict[str, int] = {}  # LOGICAL elems per spec name (wire model)
    for layer, name, spec, stored, divs in _layout(net, widths):
        sizes[name] = int(math.prod(spec.shape)) if spec.shape else 1
        zdim = _zero_dim(stored, divs, ndata) if zero else None
        gather[name] = not (ring and zdim is not None)
        if spec.owner is not None:
            continue  # shared params alias their owner's storage
        pb = _shard_elems(stored, divs) * 4  # fp32 masters
        param_bytes += pb
        udivs = list(divs)
        if zdim is not None:
            udivs[zdim] = ndata
            zero_gather_bytes += int(math.prod(stored)) * 4
        ob = _shard_elems(stored, udivs) * nslots * 4
        opt_bytes += ob
        rb = 0
        if residuals:
            # error-feedback residuals are STORED-shape fp32 buffers;
            # under the ring each data shard owns only its chunk
            relems = int(math.prod(stored)) if stored else 1
            rb = (relems // ndata if ring else relems) * 4
            residual_bytes += rb
        n, b = groups.get(layer.name, (0, 0))
        groups[layer.name] = (n + 1, b + pb + ob + rb)
    if zero and nslots and ndata > 1 and opt_bytes == param_bytes * nslots:
        notes.append(
            "zero_update declared but no param dim is divisible by the "
            f"data axis ({ndata}): every update stays replicated"
        )

    # --- activation working set ------------------------------------------
    act_itemsize = _act_itemsize(model_cfg)
    b_dev = max(1, net.batchsize // ndata)
    b_micro = max(1, b_dev // nmicro)
    act_elems = sum(
        int(math.prod(l.out_shape))
        for l in net.layers
        if not l.is_datalayer and l.out_shape
    )
    act_bytes = act_elems * b_micro * act_itemsize
    nmodel = widths.get("model", 1)
    if nmodel > 1:
        notes.append(
            "activation bytes are the unsharded upper bound (model-axis "
            "activation sharding not modeled)"
        )

    # --- serving KV pool --------------------------------------------------
    kv_bytes = kv_pool_bytes(model_cfg, widths, notes)

    # --- collectives -------------------------------------------------------
    collectives: list[tuple[str, int]] = []
    from ..ops.quantized_collective import (
        modeled_wire_bytes,
        reference_wire_bytes,
    )

    if ndata > 1:
        if ring:
            from ..parallel.collectives import reverse_topo_buckets

            specs = net.param_specs()
            buckets = reverse_topo_buckets(
                net, frozenset(sizes), gc.buckets, specs
            )
            if hier is not None:
                from ..ops.quantized_collective import (
                    modeled_wire_bytes_levels,
                )

                levels = modeled_wire_bytes_levels(
                    sizes,
                    buckets,
                    ndata,
                    intra_degree=hier[0],
                    dtype=gc.dtype,
                    gather=gather,
                )
                collectives.append(
                    (
                        "grad ring intra-slice (f32 wire)",
                        int(levels["intra"]),
                    )
                )
                collectives.append(
                    (
                        f"grad ring inter-slice ({gc.dtype} wire)",
                        int(levels["inter"]),
                    )
                )
            else:
                wire = modeled_wire_bytes(
                    sizes, buckets, ndata, dtype=gc.dtype, gather=gather
                )
                collectives.append(
                    (f"grad ring reduce ({gc.dtype} wire)", int(wire))
                )
        else:
            wire = reference_wire_bytes(sizes, ndata, scatter_only=zero)
            label = (
                "grad reduce-scatter (f32 wire)"
                if zero
                else "grad all-reduce (f32 wire)"
            )
            collectives.append((label, int(wire)))
        if zero and zero_gather_bytes:
            # constraining fresh params back to the forward layout is the
            # allgather half zero_update moved off the grad collective
            collectives.append(
                (
                    "zero param allgather (f32)",
                    int(zero_gather_bytes * (ndata - 1) / ndata),
                )
            )

    if nexpert > 1:
        for l in net.layers:
            if l.TYPE != "kMoE" or getattr(l, "dispatch", "") != "alltoall":
                continue
            seq_d = int(math.prod(l.out_shape)) if l.out_shape else 0
            # parallel/moe.py moe_ffn_a2a: two all_to_alls move
            # 2 * cf * n_local * d elements forward (dispatch + combine);
            # the backward retraces both, doubling the volume
            tokens_elems = b_micro * seq_d // nexpert
            a2a = int(
                4 * l.capacity_factor * tokens_elems * act_itemsize * nmicro
            )
            collectives.append((f"moe all-to-all ({l.name})", a2a))

    if stages > 1:
        # per-microbatch ppermute of the stage boundary activation, fwd +
        # bwd; per device = its own boundary (worst stage modeled)
        edge_elems = 0
        prev_id = None
        for l in net.layers:
            lid = l.cfg.locationid
            if (
                prev_id is not None
                and lid is not None
                and lid == prev_id + 1
            ):
                edge_elems = max(edge_elems, int(math.prod(prev_shape)))
            if lid is not None:
                prev_id, prev_shape = lid, l.out_shape or ()
        collectives.append(
            (
                "pipeline edge sends",
                2 * nmicro * edge_elems * b_micro * act_itemsize,
            )
        )

    # --- compute proxy -----------------------------------------------------
    # operand-traffic proxy for one step: every activation is produced in
    # the forward and consumed twice in the backward (~3x the activation
    # stream), and every param is read in the forward, read again in the
    # backward, and its gradient written (~3x the param stream). COST001
    # is a RATIO heuristic on top of this, not a FLOP model.
    logical_param_elems = sum(
        sizes[n] for n, s in net.param_specs().items() if s.owner is None
    )
    compute_bytes = 3 * (
        act_elems * b_dev * act_itemsize
        + logical_param_elems * act_itemsize
    )

    return CostReport(
        path=path,
        widths=widths,
        nmicro=nmicro,
        stages=stages,
        param_bytes=param_bytes,
        opt_bytes=opt_bytes,
        residual_bytes=residual_bytes,
        act_bytes=act_bytes,
        kv_bytes=kv_bytes,
        param_groups=sorted(
            ((ln, n, b) for ln, (n, b) in groups.items()),
            key=lambda t: -t[2],
        ),
        collectives=collectives,
        compute_bytes=compute_bytes,
        bubble=bubble,
        notes=notes,
    )


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _fmt_bytes(n: int | float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"  # pragma: no cover - loop always returns


def _declared_hit_rate(model_cfg: ModelConfig) -> float:
    """The operator-declared expected prefix-cache hit rate
    (``fleet { load { prefix_hit_rate } }``), clamped to [0, 1] —
    honored by the capacity discounts ONLY when
    ``serving.prefix_cache`` is actually enabled (a declared rate on a
    cache-less conf is a wish, not capacity)."""
    srv = getattr(model_cfg, "serving", None)
    fleet = getattr(model_cfg, "fleet", None)
    if (
        srv is None or srv.prefix_cache is None
        or not srv.prefix_cache.enabled
        or fleet is None or fleet.load is None
    ):
        return 0.0
    return min(1.0, max(0.0, fleet.load.prefix_hit_rate))


def serving_cost_rules(
    model_cfg: ModelConfig,
    cluster_cfg: ClusterConfig | None,
    widths: dict[str, int] | None,
    path: str,
    col: Collector,
) -> None:
    """SRV002 — SRV001's capacity sibling, config-only (no net build).

    (a) slots x block-budget admission feasibility: a declared
        ``kv_blocks`` pool that can hold fewer concurrent max-length
        sequences than the declared ``slots`` lanes — the engine
        backpressures admissions long before the decode batch fills, so
        the operator's declared concurrency is statically unreachable.
        Skipped when the window is not statically decidable (SRV001's
        convention).
    (b) full KV-pool byte sizing: the pool's modeled bytes alone exceed
        the cluster's declared ``device_hbm_bytes`` — a serving-only
        deployment OOMs at engine construction, before MEM001's
        training-footprint total even applies."""
    srv = model_cfg.serving
    if srv is None:
        return
    window = _declared_window(model_cfg)
    block_len = max(1, srv.kv_block_len)
    per_seq = window // block_len if window else 0
    hit = _declared_hit_rate(model_cfg)
    if srv.kv_blocks > 0 and per_seq > 0:
        # prefix-cache sharing discount: a hit admission SHARES its
        # cached prompt blocks instead of allocating fresh ones, so at
        # the declared fleet { load { prefix_hit_rate } } the expected
        # per-sequence block demand drops by hit_rate x the cacheable
        # prompt blocks. Without the declared rate (or with the cache
        # off) the undiscounted bound stands — sizing must not assume
        # wins the operator never promised
        shared = 0
        load = model_cfg.fleet.load if model_cfg.fleet else None
        if hit > 0 and load is not None and load.prompt_tokens > 0:
            shared = int(
                hit * (min(load.prompt_tokens, window) // block_len)
            )
        per_seq_eff = max(1, per_seq - shared)
        conc = (srv.kv_blocks - 1) // per_seq_eff  # minus the trash block
        if conc < srv.slots:
            col.emit(
                SRV002,
                path,
                f"serving kv_blocks {srv.kv_blocks} holds only {conc} "
                f"concurrent max-length sequence(s) ({per_seq_eff} "
                "blocks each"
                + (
                    f" after the prefix_hit_rate {hit:g} sharing "
                    f"discount of {shared} block(s)"
                    if shared
                    else ""
                )
                + " + the reserved trash block) but slots declares "
                f"{srv.slots} decode lanes: the declared concurrency is "
                "statically unreachable — admissions backpressure at "
                f"{conc} live sequence(s)",
                fix_hint=f"set kv_blocks >= "
                f"{srv.slots * per_seq_eff + 1} (dense-equivalent), "
                "lower slots, or enable prefix_cache to share blocks",
            )
    budget = cluster_cfg.device_hbm_bytes if cluster_cfg is not None else 0
    if budget > 0:
        notes: list[str] = []
        kv = kv_pool_bytes(model_cfg, widths or {}, notes)
        if kv > budget:
            col.emit(
                SRV002,
                path,
                f"serving KV pool alone needs {_fmt_bytes(kv)} per device "
                f"— over the declared device_hbm_bytes budget "
                f"({_fmt_bytes(budget)}): the engine OOMs at pool "
                "allocation",
                fix_hint="shrink kv_blocks/slots/max_len, shard heads "
                "over a wider model axis, or raise device_hbm_bytes",
            )


def fleet_cost_rules(
    model_cfg: ModelConfig,
    cluster_cfg: ClusterConfig | None,
    path: str,
    col: Collector,
) -> None:
    """FLT002 — per-role fleet sizing against the declared offered load
    (``fleet { load { ... } }``; FleetLoadConfig documents the capacity
    math). Host counts come from explicit peers entries, else the
    cluster's nworkers (run_from_conf's synthetic topology), else
    max_hosts; a topology whose host count the confs cannot see is
    skipped (FLT001's not-statically-decidable convention). Unified
    hosts count toward BOTH roles — an upper bound, since a real
    unified host splits its ticks between prefill and decode."""
    fleet = model_cfg.fleet
    if fleet is None or fleet.load is None:
        return
    load = fleet.load
    if load.requests_per_s <= 0 or load.ticks_per_s <= 0:
        return
    if fleet.peers:
        roles = [p.role for p in fleet.peers]
    else:
        n_hosts = (
            (cluster_cfg.nworkers if cluster_cfg is not None else 0)
            or fleet.max_hosts
        )
        if not n_hosts:
            return  # host count not statically decidable
        if fleet.role == "auto":
            np_hosts = min(n_hosts, max(1, fleet.prefill_hosts))
            roles = ["prefill"] * np_hosts + ["decode"] * (
                n_hosts - np_hosts
            )
        else:
            roles = [fleet.role] * n_hosts
    n_prefill = sum(1 for r in roles if r in ("prefill", "unified"))
    n_decode = sum(1 for r in roles if r in ("decode", "unified"))
    srv = model_cfg.serving
    slots = (
        srv.slots
        if srv is not None
        else schema.ServingConfig.FIELDS["slots"].default
    )
    chunk = (
        srv.max_prefill_chunk
        if srv is not None
        else schema.ServingConfig.FIELDS["max_prefill_chunk"].default
    )
    rps, ticks = load.requests_per_s, load.ticks_per_s
    hit = _declared_hit_rate(model_cfg)
    for role, n_hosts, per_tick, demand_tokens, knob in (
        ("decode", n_decode, slots, load.decode_tokens, "slots"),
        ("prefill", n_prefill, chunk, load.prompt_tokens,
         "max_prefill_chunk"),
    ):
        if demand_tokens <= 0:
            continue
        capacity = n_hosts * per_tick * ticks
        demand = rps * demand_tokens
        discounted = False
        if role == "prefill" and hit > 0:
            # prefix-cache discount: a hit admission skips the prefill
            # chunks its cached blocks cover, so at the declared
            # fleet { load { prefix_hit_rate } } only (1 - rate) of
            # the prompt tokens reach the prefill tier. Decode demand
            # is untouched — every token still decodes
            demand *= 1.0 - hit
            discounted = True
        if demand > capacity:
            col.emit(
                FLT002,
                path,
                f"fleet {role} capacity {capacity:.0f} tokens/s "
                f"({n_hosts} host(s) x {per_tick} {knob} x "
                f"{ticks:g} ticks/s) is below the offered load "
                f"{demand:.0f} tokens/s ({rps:g} req/s x "
                f"{demand_tokens} {role} tokens"
                + (
                    f" x (1 - prefix_hit_rate {hit:g})"
                    if discounted
                    else ""
                )
                + (
                    "; unified hosts counted toward both roles"
                    if "unified" in roles
                    else ""
                )
                + ")",
                fix_hint=f"add {role}-capable hosts, raise {knob}, or "
                "lower the declared load",
            )


def cost_rules(
    model_cfg: ModelConfig,
    cluster_cfg: ClusterConfig | None,
    widths: dict[str, int] | None,
    path: str,
    col: Collector,
    *,
    comm_fraction: float = DEFAULT_COMM_FRACTION,
) -> CostReport | None:
    """All four cost rules for one model conf; returns the CostReport
    (for ``--explain-cost``) or None when the net did not build —
    SRV002/FLT002's config-only arms run either way."""
    serving_cost_rules(model_cfg, cluster_cfg, widths, path, col)
    fleet_cost_rules(model_cfg, cluster_cfg, path, col)
    report = build_cost_model(model_cfg, widths, path)
    if report is None:
        return None
    if cluster_cfg is not None:
        report.inter_slice_bandwidth = cluster_cfg.inter_slice_bandwidth
    budget = cluster_cfg.device_hbm_bytes if cluster_cfg is not None else 0
    if budget > 0 and report.hbm_bytes > budget:
        parts = ", ".join(
            f"{label} {_fmt_bytes(b)}"
            for label, b in (
                ("params", report.param_bytes),
                ("opt slots", report.opt_bytes),
                ("residuals", report.residual_bytes),
                ("activations", report.act_bytes),
                ("KV pool", report.kv_bytes),
            )
            if b
        )
        col.emit(
            MEM001,
            path,
            f"predicted per-device footprint {_fmt_bytes(report.hbm_bytes)} "
            f"exceeds the declared device_hbm_bytes budget "
            f"({_fmt_bytes(budget)}): {parts}",
            fix_hint="shard wider (zero_update, model/expert axes), "
            "shrink the model/batch, or raise device_hbm_bytes",
        )
    # live weight rollout (serve/rollout.py): during the stage window
    # a host holds TWO resident param trees — the serving copy and the
    # staged next version — so a fleet whose steady-state footprint
    # fits can still OOM the moment a weight_ship lands. Only the
    # headroom arm fires here: a steady-state overflow is already
    # MEM001 above, and doubling down would be noise.
    ro = getattr(getattr(model_cfg, "fleet", None) or object(),
                 "rollout", None)
    if (
        ro is not None
        and (ro.version or ro.checkpoint or ro.canary)
        and budget > 0
        and report.hbm_bytes <= budget
        and report.hbm_bytes + report.param_bytes > budget
    ):
        from .net_rules import ROL001

        col.emit(
            ROL001,
            path,
            "live rollout stages a second resident param tree: "
            f"footprint {_fmt_bytes(report.hbm_bytes)} + staged params "
            f"{_fmt_bytes(report.param_bytes)} = "
            f"{_fmt_bytes(report.hbm_bytes + report.param_bytes)} "
            f"exceeds device_hbm_bytes ({_fmt_bytes(budget)}) during "
            "the stage window — the hot-swap would OOM a host that "
            "serves fine at steady state",
            fix_hint="free HBM headroom >= one param tree (shrink the "
            "KV pool / model, or raise device_hbm_bytes)",
        )
    if (
        comm_fraction > 0
        and report.compute_bytes > 0
        and report.collective_bytes
        > comm_fraction * report.compute_bytes
    ):
        ratio = report.collective_bytes / report.compute_bytes
        col.emit(
            COST001,
            path,
            f"modeled collective bytes {_fmt_bytes(report.collective_bytes)}"
            f"/step are {ratio:.2f}x the modeled compute bytes "
            f"{_fmt_bytes(report.compute_bytes)} (budget "
            f"{comm_fraction:g}): the step is communication-bound on "
            "paper before it ever runs",
            fix_hint="quantize the wire (grad_comm int8 + quantized_ring),"
            " grow the per-device batch, or narrow the data axis",
        )
    return report


# ---------------------------------------------------------------------------
# --explain-cost rendering
# ---------------------------------------------------------------------------


def render_cost_report(report: CostReport) -> str:
    """The ``--explain-cost`` table: per-component HBM bytes, per-param-
    group bytes, per-collective bytes, and the pipeline bubble."""
    w = report.widths
    axes = " ".join(
        f"{a}={w[a]}" for a in ("data", "model", "expert", "pipe", "seq")
        if w.get(a, 1) > 1
    ) or "single-device"
    lines = [f"cost model: {report.path} ({axes})"]
    lines.append("  HBM (bytes/device)")
    for label, b in (
        ("params (fp32 masters)", report.param_bytes),
        ("optimizer slots", report.opt_bytes),
        ("error-feedback residuals", report.residual_bytes),
        ("activations / microbatch", report.act_bytes),
        ("serving KV pool", report.kv_bytes),
    ):
        lines.append(f"    {label:<28} {b:>14}  {_fmt_bytes(b)}")
    lines.append(
        f"    {'total':<28} {report.hbm_bytes:>14}  "
        f"{_fmt_bytes(report.hbm_bytes)}"
    )
    if report.param_groups:
        lines.append("  param groups (params+slots+residuals, bytes/device)")
        for layer, n, b in report.param_groups:
            lines.append(
                f"    {layer:<28} {b:>14}  {_fmt_bytes(b)} "
                f"({n} param(s))"
            )
    lines.append("  collectives (bytes/device/step)")
    if report.collectives:
        for label, b in report.collectives:
            lines.append(f"    {label:<28} {b:>14}  {_fmt_bytes(b)}")
    else:
        lines.append("    (none: single-device step)")
    inter = sum(
        b for label, b in report.collectives if "inter-slice" in label
    )
    if report.inter_slice_bandwidth > 0 and inter:
        secs = inter / report.inter_slice_bandwidth
        lines.append(
            f"  inter-slice transfer/step    {secs * 1e3:>13.3f}ms  "
            f"({_fmt_bytes(inter)} at "
            f"{_fmt_bytes(report.inter_slice_bandwidth)}/s DCN)"
        )
    lines.append(
        f"  compute bytes/step (proxy)     {report.compute_bytes:>14}  "
        f"{_fmt_bytes(report.compute_bytes)}"
    )
    lines.append(
        f"  pipeline bubble                {report.bubble * 100:>13.1f}%  "
        f"(stages={report.stages}, microbatches={report.nmicro})"
    )
    for note in report.notes:
        lines.append(f"  note: {note}")
    return "\n".join(lines)
