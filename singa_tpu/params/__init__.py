"""Parameter specs, initialization, and sharing.

Replaces the reference's Param class (include/utils/param.h,
src/utils/param.cc). A parameter here is a plain jnp array living in a
name-keyed pytree; this module carries the *metadata* the reference attached
to each Param — init method + hyperparams, per-param learning-rate /
weight-decay multipliers, fan-in, and sharing (owner) links — and implements
the 6 init methods with the reference's exact fan-in scaling rules
(src/utils/param.cc:61-99).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError, ParamConfig


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """Static metadata for one parameter tensor.

    ``fan_in`` follows the reference's per-layer conventions: for an FC
    weight the *total size* vdim*hdim (layer.cc:178), for a conv weight the
    col height channels*k*k (layer.cc:49), 0 for biases.
    """

    name: str
    shape: tuple[int, ...]
    init_method: str = "kConstant"
    value: float = 1.0
    low: float = -1.0
    high: float = 1.0
    mean: float = 0.0
    std: float = 1.0
    lr_mult: float = 1.0
    wd_mult: float = 1.0
    fan_in: int = 0
    owner: str | None = None  # share_param: alias of another param's storage
    # Which array axis holds the layer's neuron dimension — the axis
    # kLayerPartition splits (reference: base_layer.h:121-128 picks dim 1 of
    # the *blob*; per-param this is dim 1 for FC weights, dim 0 for conv
    # filters/biases). None = never model-sharded.
    neuron_axis: int | None = None
    # Which array axis enumerates experts (kMoE weights) — sharded over
    # the mesh's expert axis (singa-tpu extension; the reference has no
    # MoE). None = not expert-sharded.
    expert_axis: int | None = None

    @classmethod
    def from_config(
        cls,
        cfg: ParamConfig | None,
        name: str,
        shape: tuple[int, ...],
        fan_in: int = 0,
        owner: str | None = None,
        neuron_axis: int | None = None,
        expert_axis: int | None = None,
    ) -> "ParamSpec":
        if cfg is None:
            return cls(
                name=name,
                shape=shape,
                fan_in=fan_in,
                owner=owner,
                neuron_axis=neuron_axis,
                expert_axis=expert_axis,
            )
        return cls(
            name=name,
            shape=shape,
            init_method=cfg.init_method,
            value=cfg.value,
            low=cfg.low,
            high=cfg.high,
            mean=cfg.mean,
            std=cfg.std,
            lr_mult=cfg.learning_rate_multiplier,
            wd_mult=cfg.weight_decay_multiplier,
            fan_in=fan_in,
            owner=owner,
            neuron_axis=neuron_axis,
            expert_axis=expert_axis,
        )


def init_param(rng: jax.Array, spec: ParamSpec) -> jnp.ndarray:
    """Materialize one parameter per its init method.

    Mirrors Param::Init (reference: src/utils/param.cc:61-99) including the
    quirky scaling rules: every random method multiplies by ``value`` when
    nonzero, and the SqrtFanIn family divides that scale by the respective
    sqrt term. RNG parity with the reference is distributional, not bitwise
    (it seeds C rand() with wall-clock time).
    """
    shape = spec.shape
    m = spec.init_method
    if m == "kConstant":
        return jnp.full(shape, spec.value, dtype=jnp.float32)
    if m == "kUniform":
        x = jax.random.uniform(
            rng, shape, minval=spec.low, maxval=spec.high, dtype=jnp.float32
        )
        return x * spec.value if spec.value else x
    if m == "kUniformSqrtFanIn":
        if spec.fan_in <= 0:
            raise ConfigError(f"param {spec.name!r}: kUniformSqrtFanIn needs fan_in>0")
        x = jax.random.uniform(
            rng, shape, minval=spec.low, maxval=spec.high, dtype=jnp.float32
        )
        if spec.value:
            x = x * (spec.value / jnp.sqrt(spec.fan_in / 3.0))
        return x
    if m == "kUniformSqrtFanInOut":
        x = jax.random.uniform(
            rng, shape, minval=spec.low, maxval=spec.high, dtype=jnp.float32
        )
        if spec.value:
            x = x * (spec.value / jnp.sqrt(shape[0] + shape[1]))
        return x
    if m == "kGaussain":  # [sic] reference spelling
        x = spec.mean + spec.std * jax.random.normal(rng, shape, dtype=jnp.float32)
        return x * spec.value if spec.value else x
    if m == "kGaussainSqrtFanIn":
        x = spec.mean + spec.std * jax.random.normal(rng, shape, dtype=jnp.float32)
        if spec.value:
            x = x * (spec.value / jnp.sqrt(shape[0]))
        return x
    if m == "kPretrained":
        # Resolved by the checkpoint restore path (trainer/checkpoint.py),
        # which fills these from ModelConfig.checkpoint before training.
        return jnp.zeros(shape, dtype=jnp.float32)
    raise ConfigError(f"param {spec.name!r}: unknown init method {m!r}")


def init_params(
    rng: jax.Array, specs: dict[str, ParamSpec]
) -> dict[str, jnp.ndarray]:
    """Materialize a name-keyed param pytree.

    Shared params (spec.owner set) alias their owner's array, mirroring
    Param::ShareData (reference: include/utils/param.h:55-73).
    """
    owners = {n: s for n, s in specs.items() if s.owner is None}
    keys = jax.random.split(rng, max(len(owners), 1))
    out: dict[str, jnp.ndarray] = {}
    for key, (name, spec) in zip(keys, sorted(owners.items())):
        out[name] = init_param(key, spec)
    for name, spec in specs.items():
        if spec.owner is not None:
            if spec.owner not in out:
                raise ConfigError(
                    f"param {name!r} shares unknown owner {spec.owner!r}"
                )
            if specs[spec.owner].shape != spec.shape:
                raise ConfigError(
                    f"param {name!r} shares {spec.owner!r} with mismatched shape"
                )
    return out
