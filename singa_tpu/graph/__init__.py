"""Net graph: build, topo-sort, shape-infer, and run layer DAGs."""

from .builder import Net, build_net, topo_sort

__all__ = ["Net", "build_net", "topo_sort"]
