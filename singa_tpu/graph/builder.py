"""Neural-net DAG builder.

Replaces NeuralNet::ConstructNeuralNet (reference:
src/worker/neuralnet.cc:72-110) and the Worker's phase filtering
(src/worker/worker.cc:69-95): layers are filtered by ``exclude`` for the
requested phase, topo-sorted from their ``srclayers`` edges, instantiated
through the registry, and shape-inferred in order. The partition rewriter
(PartitionNeuralNet, neuralnet.cc:112-323) has NO counterpart here by
design — partitioning is expressed as GSPMD shardings over the unmodified
graph (see singa_tpu.parallel), which is the entire point of the TPU-native
re-design.

``Net.forward`` is a pure function of (params, batch, rng) and is traced
into the jitted train step; the reference's Forward hot loop
(worker.cc:240-268) with its bridge spins and WaitUpdate blocking dissolves
into one XLA program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError, LayerConfig, ModelConfig, NetConfig
from ..layers import Layer, create_layer
from ..layers.connector import SliceLayer
from ..params import ParamSpec
from .kahn import kahn_order

PHASES = ("kTrain", "kValidation", "kTest")


def topo_sort(configs: list[LayerConfig]) -> list[LayerConfig]:
    """Kahn's algorithm over srclayers edges, stable wrt config order
    (the reference DFS-sorts in Graph::Sort, src/utils/graph.cc:80-101).

    Fail-fast wrapper over the shared core (graph/kahn.py — the same
    loop lint's report-all cycle pass uses): unknown srclayers and
    cycles abort the build with ConfigError."""
    by_name = {c.name: c for c in configs}
    if len(by_name) != len(configs):
        names = [c.name for c in configs]
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ConfigError(f"duplicate layer names after phase filter: {dupes}")
    for c in configs:
        for src in c.srclayers:
            if src not in by_name:
                raise ConfigError(
                    f"layer {c.name!r} references unknown srclayer {src!r}"
                )
    order, residue = kahn_order(
        [c.name for c in configs], {c.name: c.srclayers for c in configs}
    )
    if residue:
        raise ConfigError(f"cycle in layer graph involving {sorted(residue)}")
    return [by_name[n] for n in order]


class Net:
    """An ordered, shape-inferred layer DAG for one phase."""

    def __init__(self, layers: list[Layer], phase: str):
        self.phase = phase
        self.layers = layers
        #: set by the trainer when the cluster declares a pipe axis and
        #: the net places layers by locationid (graph/pipeline_plan.py)
        self.pipeline_plan = None
        self.pipeline_mesh = None
        #: {param name: logical shape} for params whose STORED arrays are
        #: pad-to-multiple for an indivisible kLayerPartition dim
        #: (parallel/shardings.py param_paddings); forward slices the
        #: stored array back to the logical shape before layers see it
        self.param_logical: dict[str, tuple] = {}
        self.name2layer = {l.name: l for l in layers}
        self.datalayers = [l for l in layers if l.is_datalayer]
        self.parserlayers = [l for l in layers if l.is_parserlayer]
        self.losslayers = [l for l in layers if l.is_losslayer]
        # consumer lists drive Slice output routing (k-th dst gets slice k,
        # reference base_layer.cc:136-151)
        self.dstlayers: dict[str, list[str]] = {l.name: [] for l in layers}
        for l in layers:
            for src in l.srclayers:
                self.dstlayers[src].append(l.name)

    # ---------------- build ----------------

    def setup(self) -> None:
        shapes: dict[str, tuple] = {}
        batchsize = 0
        for layer in self.layers:
            src_shapes = [shapes[s] for s in layer.srclayers]
            out = layer.setup(src_shapes, batchsize)
            layer.validate([self.name2layer[s] for s in layer.srclayers])
            if layer.is_datalayer:
                batchsize = layer.batchsize
            if isinstance(layer, SliceLayer):
                # consumers each see one slice
                shapes[layer.name] = out
            else:
                shapes[layer.name] = out
            layer.out_shape = out
        self.batchsize = batchsize

    def bind_mesh(self, mesh) -> None:
        """Attach the device mesh to every layer (static metadata read by
        mesh-aware layers — ring attention, kMoE). The trainer calls this
        once the mesh is resolved; nets built without a trainer keep
        mesh=None and the layers' single-device fallbacks."""
        for layer in self.layers:
            layer.mesh = mesh

    def param_specs(self) -> dict[str, ParamSpec]:
        specs: dict[str, ParamSpec] = {}
        for layer in self.layers:
            for name, spec in layer.param_specs().items():
                if name in specs:
                    raise ConfigError(f"duplicate param name {name!r}")
                specs[name] = spec
        return specs

    def buffer_specs(self) -> dict:
        """Non-trainable state (BufferSpec) declared by stateful layers."""
        specs = {}
        for layer in self.layers:
            specs.update(layer.buffer_specs())
        return specs

    def init_buffers(self) -> dict[str, jnp.ndarray]:
        return {
            name: jnp.full(spec.shape, spec.init, dtype=jnp.float32)
            for name, spec in self.buffer_specs().items()
        }

    # ---------------- trace ----------------

    def resolve_params(self, params: dict) -> dict:
        """Param view every graph walk shares (forward AND the serving
        tier's incremental decode, serve/conf_decode.py): shared params
        resolve through their owner's array (ParamSpec.owner), and
        pad-to-multiple storage (uneven kLayerPartition dims) slices
        back to the logical shape. Ellipsis keeps any leading replica
        axis (ReplicaTrainer stacks params as (R, ...)). The slice of
        the zero tail has zero cotangent, so gradients/updater slots on
        the tail stay exactly zero."""
        resolved = dict(params)
        for layer in self.layers:
            for name, spec in layer.param_specs().items():
                if spec.owner is not None:
                    resolved[name] = params[spec.owner]
        for name, logical in self.param_logical.items():
            v = resolved.get(name)
            if v is not None and v.shape[-len(logical):] != tuple(logical):
                resolved[name] = v[
                    (Ellipsis, *(slice(0, s) for s in logical))
                ]
        return resolved

    def forward(
        self,
        params: dict[str, jnp.ndarray],
        batch: dict[str, Any],
        *,
        training: bool,
        rng: jax.Array | None = None,
        buffers: dict[str, jnp.ndarray] | None = None,
        return_buffers: bool = False,
        return_acts: bool = False,
        layer_hook=None,
    ):
        """Run all layers; returns (total_loss, {losslayer: metrics}).

        ``batch`` maps each data layer's name to its input dict
        ({"image": ..., "label": ...}); shared params resolve through their
        owner's array (ParamSpec.owner). With ``return_acts`` the per-layer
        activation dict is appended — the debug-mode hook (the reference
        dumps per-layer L1 norms, neuralnet.cc:350-378). ``layer_hook``
        optionally overrides a layer's compute: called as
        hook(layer, resolved_params, inputs, layer_rng); a non-None return
        replaces layer.apply — this is how the CD trainer swaps RBM layers
        to Gibbs-chain updates without re-implementing the traversal.

        ``buffers`` feeds stateful layers (batch norm running stats);
        omitted, they use their init values. With ``return_buffers`` the
        post-step buffer dict is appended (before acts): the trainer
        carries it between steps.
        """
        if buffers is None:
            buffers = self.init_buffers()
        new_buffers = dict(buffers)
        resolved = self.resolve_params(params)

        acts: dict[str, Any] = {}
        slice_cursor: dict[str, int] = {}
        total_loss = jnp.float32(0.0)
        metrics: dict[str, dict[str, jnp.ndarray]] = {}
        staged_names: set[str] = set()
        if self.pipeline_plan is not None:
            staged_names = {
                l.name for st in self.pipeline_plan.stages for l in st
            }
        for i, layer in enumerate(self.layers):
            if layer.name in staged_names:
                # the whole staged region executes as one GPipe schedule
                # when its first layer is reached; later staged layers
                # are already covered
                plan = self.pipeline_plan
                if layer is plan.stages[0][0]:
                    from .pipeline_plan import pipeline_forward_region

                    acts[plan.exits[-1]] = pipeline_forward_region(
                        plan, resolved, acts[plan.entry_src],
                        self.pipeline_mesh,
                    )
                continue
            if layer.is_datalayer:
                inputs = [batch[layer.name]]
            else:
                inputs = []
                for src in layer.srclayers:
                    val = acts[src]
                    if isinstance(self.name2layer.get(src), SliceLayer):
                        k = slice_cursor.get(src, 0)
                        slice_cursor[src] = k + 1
                        val = val[k]
                    inputs.append(val)
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            out = None
            if layer_hook is not None:
                out = layer_hook(layer, resolved, inputs, lrng)
            if out is None:
                if layer.has_buffers:
                    out, updates = layer.apply_stateful(
                        resolved, buffers, inputs,
                        training=training, rng=lrng,
                    )
                    new_buffers.update(updates)
                else:
                    out = layer.apply(
                        resolved, inputs, training=training, rng=lrng
                    )
            if layer.is_losslayer:
                loss, m = out
                total_loss = total_loss + loss
                metrics[layer.name] = m
                acts[layer.name] = loss
            elif layer.has_aux_loss:
                # e.g. kMoE load balancing: apply returns (out, aux);
                # aux joins the total loss at the layer's declared weight
                out, aux = out
                total_loss = total_loss + layer.aux_weight * aux
                acts[layer.name] = out
            else:
                acts[layer.name] = out
        extra = []
        if return_buffers:
            extra.append(new_buffers)
        if return_acts:
            extra.append(acts)
        return (total_loss, metrics, *extra)

    # ---------------- observability ----------------

    def to_json(self) -> dict:
        """Node-link dump matching NeuralNet::ToString's shape
        (reference: neuralnet.cc:325-332, src/utils/graph.cc:8-59)."""
        nodes = [
            {
                "id": l.name,
                "type": l.TYPE,
                "shape": list(l.out_shape or ()),
                "partition_dim": l.partition_dim,
            }
            for l in self.layers
        ]
        links = [
            {"source": src, "target": l.name}
            for l in self.layers
            for src in l.srclayers
        ]
        return {"phase": self.phase, "nodes": nodes, "links": links}


def active_phases(model_cfg: ModelConfig) -> list[str]:
    """Phases this job actually builds nets for (Trainer.__init__ builds
    from this list): kTrain always, kTest/kValidation only when their
    step counts are set.
    Lint passes check exactly these — a conf whose two ``data`` layers
    exclude kTrain/kTest respectively is fine unless validation_steps
    makes the kValidation net (where both would be live) real."""
    phases = ["kTrain"]
    if model_cfg.test_steps:
        phases.append("kTest")
    if model_cfg.validation_steps:
        phases.append("kValidation")
    return phases


def filter_phase(net_cfg: NetConfig, phase: str) -> list[LayerConfig]:
    """Drop layers whose ``exclude`` lists the phase (worker.cc:69-95)."""
    if phase not in PHASES:
        raise ConfigError(f"unknown phase {phase!r}")
    return [l for l in net_cfg.layer if phase not in (l.exclude or [])]


def build_net(model_cfg: ModelConfig, phase: str = "kTrain") -> Net:
    """Config -> phase-filtered, topo-sorted, shape-inferred Net."""
    if model_cfg.neuralnet is None:
        raise ConfigError("model config has no neuralnet block")
    configs = filter_phase(model_cfg.neuralnet, phase)
    if not configs:
        raise ConfigError(f"no layers left for phase {phase}")
    order = topo_sort(configs)
    net_partition = model_cfg.neuralnet.partition_type
    net = Net([create_layer(c, net_partition) for c in order], phase)
    net.setup()
    return net
