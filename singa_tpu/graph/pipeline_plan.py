"""Pipeline-parallel execution of a config net, staged by ``locationid``.

The reference's ``locationid`` places layers on different workers with
blocking bridge handshakes and no microbatch interleaving
(base_layer.h:151-165; SURVEY §2.5 "layer placement without
pipelining"). Here the same config field drives the real thing: layers
sharing a locationid form a pipeline stage, and the schedule is
parallel/pipeline.py's GPipe scan — activations hop stage-to-stage over
ICI ppermute while every stage works on a different microbatch.

Scope honesty: what is pipelined is the IN-STEP COMPUTE. Stage params
are STORED replicated (param_shardings has no pipe-axis placement;
stack_stage_params restacks them inside each jitted step under a pipe
sharding constraint), so pipeline parallelism here does not yet reduce
per-device parameter/optimizer MEMORY — the stacked-storage layout
(params held as (P, ...) leaves sharded over pipe end-to-end, with
updater slots and checkpoints following) is the known next step.

Contract (validated by plan_stages, errors cite this module):
  * locationids are exactly 0..P-1 where P = the pipe axis width;
  * staged layers sit contiguously in topo order, grouped by stage;
  * every stage consumes ONE external activation (stage 0: the prologue
    exit; stage s: stage s-1's exit) — residual taps inside a stage are
    fine, taps across stages are not;
  * stages are structurally identical (same layer-type sequence, same
    param shapes, same activation shape) so stage params stack into
    (P, ...) leaves — the transformer-block case, and the same
    shape-invariance rule the reference asserts after partitioning
    (neuralnet.cc:187-193);
  * staged layers need no rng and no buffers (no dropout/batch-norm
    inside stages — raise at plan time, not silently).

Layers before the staged region (data/parser/embedding) and after it
(final norm/head/loss) run replicated on every device, outside the
pipeline schedule.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..config.schema import ConfigError


@dataclasses.dataclass
class PipelinePlan:
    nstages: int
    nmicro: int
    #: per-stage layer lists, topo order inside each stage
    stages: list[list]
    #: the one external layer name every stage-0 layer may reference
    entry_src: str
    #: stage exit layer name per stage (output of the stage)
    exits: list[str]
    #: param names by stage, aligned position-for-position with stage 0
    param_names: list[list[str]]


def plan_stages(net, npipe: int, nmicro: int = 0) -> PipelinePlan | None:
    """Group ``net``'s explicitly-placed layers into pipeline stages.

    Returns None when the net declares no placement (no layer sets
    locationid, or all share one id) — the caller then runs the plain
    forward. Raises ConfigError when a declared placement violates the
    contract above.
    """
    staged = [l for l in net.layers if l.cfg.locationid is not None]
    ids = sorted({l.cfg.locationid for l in staged})
    if len(ids) < 2:
        return None
    if ids != list(range(npipe)):
        raise ConfigError(
            f"pipeline: locationids {ids} must be exactly 0..{npipe - 1} "
            f"(the cluster's npipes_per_group)"
        )
    for l in staged:
        if l.is_datalayer or l.is_parserlayer or l.is_losslayer:
            raise ConfigError(
                f"pipeline: layer {l.name!r} ({l.TYPE}) cannot be staged"
            )
        if l.has_buffers:
            raise ConfigError(
                f"pipeline: stateful layer {l.name!r} cannot be staged"
            )
        if l.TYPE == "kDropout":
            raise ConfigError(
                f"pipeline: {l.name!r}: dropout inside stages unsupported "
                "(stage functions run without rng)"
            )
        if l.has_aux_loss:
            raise ConfigError(
                f"pipeline: {l.name!r} ({l.TYPE}) cannot be staged — its "
                "auxiliary loss has no path out of the pipeline region"
            )

    # contiguity in topo order, grouped by increasing stage id
    order = [l for l in net.layers if l.cfg.locationid is not None]
    first = next(
        i for i, l in enumerate(net.layers) if l.cfg.locationid is not None
    )
    block = net.layers[first : first + len(order)]
    if [l.name for l in block] != [l.name for l in order]:
        raise ConfigError(
            "pipeline: staged layers must be contiguous in topo order"
        )
    seen_ids = [l.cfg.locationid for l in order]
    if seen_ids != sorted(seen_ids):
        raise ConfigError(
            f"pipeline: stage ids must be non-decreasing in topo order, "
            f"got {seen_ids}"
        )
    stages = [
        [l for l in order if l.cfg.locationid == s] for s in range(npipe)
    ]

    # every stage consumes exactly one external activation
    entry_src = None
    exits = []
    for s, layers in enumerate(stages):
        names = {l.name for l in layers}
        external = set()
        for l in layers:
            external.update(src for src in l.srclayers if src not in names)
        expected = {exits[-1]} if s else None
        if s == 0:
            if len(external) != 1:
                raise ConfigError(
                    f"pipeline: stage 0 must consume one external "
                    f"activation, got {sorted(external)}"
                )
            entry_src = external.pop()
        elif external != expected:
            raise ConfigError(
                f"pipeline: stage {s} must consume only stage {s - 1}'s "
                f"exit {sorted(expected)}, got {sorted(external)}"
            )
        # the stage exit: the unique layer no other stage member consumes
        consumed = {src for l in layers for src in l.srclayers}
        tails = [l.name for l in layers if l.name not in consumed]
        if len(tails) != 1:
            raise ConfigError(
                f"pipeline: stage {s} must have one exit layer, got {tails}"
            )
        exits.append(tails[0])

    # structural identity across stages
    sig0 = [(l.TYPE, tuple(l.out_shape)) for l in stages[0]]
    specs0 = [
        sorted((n.split("/", 1)[1], sp.shape)
               for n, sp in l.param_specs().items())
        for l in stages[0]
    ]
    param_names = []
    for s, layers in enumerate(stages):
        sig = [(l.TYPE, tuple(l.out_shape)) for l in layers]
        if sig != sig0:
            raise ConfigError(
                f"pipeline: stage {s} structure {sig} != stage 0 {sig0} "
                "(stages must be identical for stacked params)"
            )
        specs = [
            sorted((n.split("/", 1)[1], sp.shape)
                   for n, sp in l.param_specs().items())
            for l in layers
        ]
        if specs != specs0:
            raise ConfigError(
                f"pipeline: stage {s} param shapes differ from stage 0"
            )
        names = []
        for l in layers:
            names.extend(sorted(l.param_specs()))
        param_names.append(names)

    if nmicro <= 0:
        nmicro = npipe
    return PipelinePlan(
        nstages=npipe,
        nmicro=nmicro,
        stages=stages,
        entry_src=entry_src,
        exits=exits,
        param_names=param_names,
    )


def stage_fn_for(plan: PipelinePlan):
    """-> f(stage_params_one, act) applying ONE stage's layer chain.

    ``stage_params_one`` is keyed by stage-0 param names (the stacked
    leaves' identity); stage 0's layer objects supply the compute —
    legitimate because plan_stages proved the stages structurally
    identical.
    """
    layers = plan.stages[0]
    entry = plan.entry_src
    exit_name = plan.exits[0]

    def fn(params_one, act):
        acts = {entry: act}
        for layer in layers:
            inputs = [acts[src] for src in layer.srclayers]
            acts[layer.name] = layer.apply(
                params_one, inputs, training=True, rng=None
            )
        return acts[exit_name]

    return fn


def stack_stage_params(plan: PipelinePlan, params: dict) -> dict:
    """Stack per-stage arrays into (nstages, ...) leaves keyed by the
    stage-0 names. Runs inside the jitted step; under the pipe-axis
    sharding constraint each stack lands distributed, not replicated."""
    out = {}
    for pos, name0 in enumerate(plan.param_names[0]):
        out[name0] = jnp.stack(
            [params[plan.param_names[s][pos]] for s in range(plan.nstages)]
        )
    return out


def pipeline_forward_region(plan: PipelinePlan, params, x, mesh):
    """The staged region: microbatch, GPipe scan, un-microbatch."""
    from ..parallel.pipeline import pipeline_apply

    b = x.shape[0]
    if b % plan.nmicro:
        raise ConfigError(
            f"pipeline: batch {b} not divisible by {plan.nmicro} microbatches"
        )
    xm = x.reshape(plan.nmicro, b // plan.nmicro, *x.shape[1:])
    stacked = stack_stage_params(plan, params)
    ym = pipeline_apply(stage_fn_for(plan), stacked, xm, mesh)
    return ym.reshape(b, *ym.shape[2:])
