"""Shared Kahn's-algorithm core for srclayers DAGs.

Two call sites used to hand-mirror this loop (and the duplicate-edge fix
of r5 had to land in both): ``graph.builder.topo_sort`` (fail-fast — a
cycle aborts the build) and ``lint.net_rules._cycle_members``
(report-all — lint wants the residue, not an exception). This module is
the single copy; the callers keep their own error policies.

The reference DFS-sorts in Graph::Sort (src/utils/graph.cc:80-101); Kahn
with a FIFO ready queue gives the same topological guarantee while being
stable with respect to the input order, which the builder relies on for
deterministic layer ordering.
"""

from __future__ import annotations

from typing import Mapping, Sequence


def kahn_order(
    names: Sequence[str], srcs: Mapping[str, Sequence[str]]
) -> tuple[list[str], set[str]]:
    """Kahn's algorithm over ``name -> list of source names`` edges.

    Returns ``(order, residue)``: ``order`` is a topological order of the
    acyclic part, stable wrt ``names`` order (FIFO ready queue);
    ``residue`` is the set of names on (or downstream of) a cycle — empty
    iff the graph is a DAG. Edges whose source is not in ``names`` are
    ignored (callers own dangling-edge reporting: builder raises,
    NET001 diagnoses). Duplicate edges count per occurrence — a layer may
    list the same src twice (e.g. concat of a layer with itself), so every
    occurrence must be removed when the source is emitted.
    """
    nameset = set(names)
    indeg = {
        n: sum(1 for s in srcs.get(n, ()) if s in nameset) for n in names
    }
    order: list[str] = []
    ready = [n for n in names if indeg[n] == 0]
    while ready:
        cur = ready.pop(0)
        order.append(cur)
        for n in names:
            deps = srcs.get(n, ())
            if cur in deps:
                indeg[n] -= list(deps).count(cur)
                if indeg[n] == 0:
                    ready.append(n)
    residue = {n for n in names if indeg[n] > 0}
    return order, residue
