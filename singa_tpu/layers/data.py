"""Data + parser layers.

Data layers (kShardData, kLMDBData) are the host/device boundary: at build
time they open their source to learn the sample shape (exactly like
ShardDataLayer::Setup reading one record, reference layer.cc:662-672), and at
run time the trainer feeds their batches in as jitted-step inputs. Their
``apply`` just forwards that external input.

Parser layers (kMnistImage, kRGBImage, kLabel) are elementwise math and run
*inside* the jitted step where XLA fuses them (the reference runs them on the
prefetch thread, base_layer.h:469-560).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..config.schema import ConfigError
from ..data.pipeline import load_shard_arrays
from .base import Layer, Shape


class _ArrayDataLayer(Layer):
    """Shared data-layer shape: open the source at build time to learn the
    sample shape (ShardDataLayer::Setup reads one record the same way,
    layer.cc:662-672), hold the decoded arrays, forward the fed batch."""

    is_datalayer = True
    LOADER: staticmethod  # (path) -> (images, labels)

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.data_param
        if p is None or not p.path or not p.batchsize:
            raise ConfigError(
                f"layer {self.name!r}: data_param.path and batchsize required"
            )
        self.path = p.path
        self.batchsize = p.batchsize
        self.random_skip = p.random_skip
        self.images, self.labels = type(self).LOADER(self.path)
        self.sample_shape = tuple(self.images.shape[1:])
        return (self.batchsize, *self.sample_shape)

    def apply(self, params, inputs, *, training, rng=None):
        # inputs[0] is the externally-fed batch dict {"image","label"}
        return inputs[0]


class ShardDataLayer(_ArrayDataLayer):
    """kShardData (reference: layer.cc:646-673)."""

    TYPE = "kShardData"
    LOADER = staticmethod(load_shard_arrays)


class LMDBDataLayer(_ArrayDataLayer):
    """kLMDBData (reference: layer.cc:237-328): reads a Caffe LMDB through
    the pure-Python B+tree reader (singa_tpu/data/lmdbio.py — no liblmdb
    in this image), converting each Datum to the record layout
    (datum_to_image_record = the reference's ConvertDatumToSingleLabel
    ImageRecord, layer.cc:306-328). Cursor wraparound becomes the batch
    pipeline's modular indexing."""

    TYPE = "kLMDBData"

    @staticmethod
    def LOADER(path):
        from ..data.pipeline import load_lmdb_arrays

        return load_lmdb_arrays(path)


class MnistImageLayer(Layer):
    """kMnistImage (reference: layer.cc:381-473): uint8 pixels ->
    float (x / norm_a) - norm_b, plus the elastic-distortion pipeline the
    reference configures but ships commented out (layer.cc:408-440):
    kernel/sigma/alpha Gaussian displacement fields, beta rotation/shear,
    gamma rescale — implemented for real in singa_tpu/ops/distortion.py
    and applied train-side inside the jitted step. ``resize`` bilinearly
    resizes (the reference's live code top-left-crops to ``resize``
    instead, layer.cc:441-448 — a bug its disabled warpAffine would have
    fixed; we implement the intended behavior)."""

    TYPE = "kMnistImage"
    is_parserlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.mnist_param
        self.norm_a = p.norm_a if p else 1.0
        self.norm_b = p.norm_b if p else 0.0
        self.kernel = p.kernel if p else 0
        self.sigma = p.sigma if p else 0.0
        self.alpha = p.alpha if p else 0.0
        self.beta = p.beta if p else 0.0
        self.gamma = p.gamma if p else 0.0
        src = src_shapes[0]  # the data layer's (batch, H, W) or (b,1,H,W)
        if len(src) < 3:
            raise ConfigError(f"layer {self.name!r}: expects image records")
        if len(src) == 4 and src[1] != 1:
            raise ConfigError(
                f"layer {self.name!r}: kMnistImage is single-channel; got "
                f"C={src[1]} records (use kRGBImage)"
            )
        size = src[-1]
        if src[-2] != size:
            raise ConfigError(f"layer {self.name!r}: MNIST images must be square")
        self.resize = (p.resize if p else 0) or size
        return (src[0], self.resize, self.resize)

    def apply(self, params, inputs, *, training, rng=None):
        import jax

        x = inputs[0]["image"].astype(jnp.float32)
        if x.ndim == 4 and x.shape[1] == 1:
            # LMDB datums carry an explicit C=1 dim; records from idx
            # files don't — normalize to (N, H, W) as setup declared
            x = x[:, 0]
        if self.resize != x.shape[-1]:
            x = jax.image.resize(
                x, (*x.shape[:-2], self.resize, self.resize), "linear"
            )
        distorting = (self.alpha and self.kernel) or self.beta or self.gamma
        if training and rng is not None and distorting:
            from ..ops.distortion import distort

            x = distort(
                x, jax.random.fold_in(rng, 23),
                kernel=self.kernel, sigma=self.sigma, alpha=self.alpha,
                beta=self.beta, gamma=self.gamma,
            )
        return x / self.norm_a - self.norm_b


class RGBImageLayer(Layer):
    """kRGBImage (reference: layer.cc:573-643): scale, random crop, random
    mirror. Crop/mirror are train-time augmentations driven by the step rng;
    eval uses a deterministic center crop like Caffe's convention."""

    TYPE = "kRGBImage"
    is_parserlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.rgbimage_param
        self.scale = p.scale if p else 1.0
        self.cropsize = p.cropsize if p else 0
        self.mirror = p.mirror if p else False
        src = src_shapes[0]
        if len(src) != 4:
            raise ConfigError(f"layer {self.name!r}: expects (N,C,H,W) records")
        n, c, h, w = src
        self.mean = None
        if p and p.meanfile:
            mean = np.load(p.meanfile)
            if tuple(mean.shape) != (c, h, w):
                raise ConfigError(
                    f"layer {self.name!r}: meanfile shape {mean.shape} != "
                    f"record shape {(c, h, w)}"
                )
            self.mean = mean.astype(np.float32)
        if self.cropsize:
            return (n, c, self.cropsize, self.cropsize)
        return src

    def apply(self, params, inputs, *, training, rng=None):
        import jax

        x = inputs[0]["image"].astype(jnp.float32)
        if self.mean is not None:
            # full-size mean subtracted before crop, like the loader-side
            # subtraction in data_source.cc:158-173
            x = x - jnp.asarray(self.mean)
        n, c, h, w = x.shape
        if self.cropsize:
            cs = self.cropsize
            if training and rng is not None:
                rh, rw = jax.random.split(rng)
                hoff = jax.random.randint(rh, (), 0, h - cs + 1)
                woff = jax.random.randint(rw, (), 0, w - cs + 1)
            else:
                hoff = (h - cs) // 2
                woff = (w - cs) // 2
            x = jax.lax.dynamic_slice(
                x, (0, 0, hoff, woff), (n, c, cs, cs)
            )
        if self.mirror and training and rng is not None:
            flip = jax.random.bernoulli(jax.random.fold_in(rng, 1), 0.5, (n,))
            x = jnp.where(flip[:, None, None, None], x[..., ::-1], x)
        if self.scale:
            x = x * self.scale
        return x


class LabelLayer(Layer):
    """kLabel (reference: layer.cc:217-233)."""

    TYPE = "kLabel"
    is_parserlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        return (src_shapes[0][0],)

    def apply(self, params, inputs, *, training, rng=None):
        return inputs[0]["label"].astype(jnp.int32)
