"""Connector layers: Slice, Concate, Split, BridgeSrc/BridgeDst.

In the reference these are the partition plumbing: the graph rewriter
inserts them to split/concatenate blobs across intra-group partitions and
to ship activations between processes over ZeroMQ
(src/worker/neuralnet.cc:198-323, src/worker/base_layer.cc:39-191). In the
TPU-native design that role is played by GSPMD: sharding annotations make
XLA insert the equivalent collectives inside the one compiled program. The
layers still exist so (a) reference job files that name them parse and run,
and (b) explicit in-graph slice/concat dataflow keeps working.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..config.schema import ConfigError
from .base import Layer, Shape, require_one_src


class SliceLayer(Layer):
    """kSlice (reference: base_layer.cc:114-173): split the input into
    slice_num equal parts along slice_dimension; output k feeds the k-th
    dstlayer. The reference gives the last partition the remainder
    (base_layer.cc:127-128); XLA wants even shards, so we require even
    divisibility and say so (SURVEY hard-part #3)."""

    TYPE = "kSlice"
    is_connectorlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.slice_param
        if p is None or not p.slice_num:
            raise ConfigError(f"layer {self.name!r}: slice_param required")
        self.dim, self.num = p.slice_dimension, p.slice_num
        src = require_one_src(self, src_shapes)
        if src[self.dim] % self.num:
            raise ConfigError(
                f"layer {self.name!r}: dim {self.dim} size {src[self.dim]} "
                f"not divisible by slice_num {self.num} (XLA shards evenly; "
                "pad or round the net width)"
            )
        out = list(src)
        out[self.dim] //= self.num
        return tuple(out)

    def apply(self, params, inputs, *, training, rng=None):
        return jnp.split(inputs[0], self.num, axis=self.dim)


class ConcateLayer(Layer):
    """kConcate (reference: base_layer.cc:85-110; its compute is a stub —
    ours is real)."""

    TYPE = "kConcate"
    is_connectorlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.concate_param
        if p is None:
            raise ConfigError(f"layer {self.name!r}: concate_param required")
        self.dim = p.concate_dimension
        out = list(src_shapes[0])
        out[self.dim] = sum(s[self.dim] for s in src_shapes)
        return tuple(out)

    def apply(self, params, inputs, *, training, rng=None):
        return jnp.concatenate(inputs, axis=self.dim)


class SplitLayer(Layer):
    """kSplit (reference: base_layer.cc:175-191): fan the same blob out to
    num_splits consumers. Identity in a functional graph."""

    TYPE = "kSplit"
    is_connectorlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        return inputs[0]


class _BridgeLayer(Layer):
    """Bridges became XLA resharding: inside one jitted program a
    location-crossing edge is just an array with a different sharding, so
    both bridge halves are identity. Kept for job-file parity
    (base_layer.h:264-312)."""

    is_connectorlayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        return inputs[0]


class BridgeSrcLayer(_BridgeLayer):
    TYPE = "kBridgeSrc"


class BridgeDstLayer(_BridgeLayer):
    TYPE = "kBridgeDst"
