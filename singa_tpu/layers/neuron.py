"""Neuron layers: Convolution, InnerProduct, ReLU, Tanh, Dropout, LRN,
Pooling (reference: src/worker/layer.cc, include/worker/layer.h:28-198)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .. import ops
from ..config.schema import ConfigError
from .base import Layer, Shape, feature_dim, require_one_src


class ConvolutionLayer(Layer):
    """kConvolution (reference: layer.cc:17-123).

    Weight is stored in the reference's (num_filters, channels*k*k) col
    layout; ops.conv2d reshapes it to OIHW for the MXU. fan_in for init is
    col_height = channels*k*k (layer.cc:49).
    """

    TYPE = "kConvolution"
    CONNECTION = "kOneToAll"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.convolution_param
        if p is None or not p.kernel:
            raise ConfigError(f"layer {self.name!r}: convolution_param.kernel required")
        src = require_one_src(self, src_shapes)
        if len(src) == 3:  # (N, H, W) -> implicit single channel
            channels, height, width = 1, src[1], src[2]
        elif len(src) == 4:
            channels, height, width = src[1], src[2], src[3]
        else:
            raise ConfigError(f"layer {self.name!r}: conv needs 3/4-D input, got {src}")
        self.kernel, self.stride, self.pad = p.kernel, p.stride, p.pad
        self.num_filters = p.num_filters
        self.channels = channels
        conv_h = (height + 2 * self.pad - self.kernel) // self.stride + 1
        conv_w = (width + 2 * self.pad - self.kernel) // self.stride + 1
        col_height = channels * self.kernel * self.kernel
        self.wname = self._declare_param(
            0,
            "weight",
            (self.num_filters, col_height),
            fan_in=col_height,
            neuron_axis=0,  # kLayerPartition splits num_filters (layer.cc:54-61)
        )
        self.bias_term = p.bias_term
        if self.bias_term:
            self.bname = self._declare_param(
                1, "bias", (self.num_filters,), neuron_axis=0
            )
        return (src[0], self.num_filters, conv_h, conv_w)

    def apply(self, params, inputs, *, training, rng=None):
        x = inputs[0]
        if x.ndim == 3:
            x = x[:, None]  # add channel dim
        bias = params[self.bname] if self.bias_term else None
        return ops.conv2d(
            x, params[self.wname], bias, stride=self.stride, pad=self.pad
        )


class InnerProductLayer(Layer):
    """kInnerProduct (reference: layer.cc:162-213). Flattens the input to
    (batch, vdim); weight (vdim, hdim) with the reference's quirky
    fan_in = vdim*hdim (layer.cc:178)."""

    TYPE = "kInnerProduct"
    CONNECTION = "kOneToAll"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.inner_product_param
        if p is None or not p.num_output:
            raise ConfigError(
                f"layer {self.name!r}: inner_product_param.num_output required"
            )
        src = require_one_src(self, src_shapes)
        vdim = feature_dim(src)
        self.vdim, self.hdim = vdim, p.num_output
        self.wname = self._declare_param(
            0,
            "weight",
            (vdim, self.hdim),
            fan_in=vdim * self.hdim,
            neuron_axis=1,  # kLayerPartition splits num_output (layer.cc:177-184)
        )
        self.bias_term = p.bias_term
        if self.bias_term:
            self.bname = self._declare_param(
                1, "bias", (self.hdim,), neuron_axis=0
            )
        return (src[0], self.hdim)

    def apply(self, params, inputs, *, training, rng=None):
        w = params[self.wname]
        # align to the weight dtype (bf16 under compute_dtype) so the
        # matmul doesn't silently promote back to fp32
        x = inputs[0].reshape(inputs[0].shape[0], -1).astype(w.dtype)
        out = x @ w
        if self.bias_term:
            out = out + params[self.bname]
        return out


class ReLULayer(Layer):
    """kReLU (reference: layer.cc:543-569)."""

    TYPE = "kReLU"

    def setup(self, src_shapes, batchsize):
        self.negative_slope = (
            self.cfg.relu_param.negative_slope if self.cfg.relu_param else 0.0
        )
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        return ops.relu(inputs[0], self.negative_slope)


class TanhLayer(Layer):
    """kTanh — always the LeCun scaled tanh, like the reference
    (layer.cc:694-701 uses op::stanh unconditionally; TanhProto's scale
    fields are parsed but ignored there too)."""

    TYPE = "kTanh"

    def setup(self, src_shapes, batchsize):
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        return ops.stanh(inputs[0])


class SigmoidLayer(Layer):
    """kSigmoid — singa-tpu extension (the reference ships op::sigmoid in
    cxxnet_op.h:14-23 but registers no layer for it; needed for the RBM
    path)."""

    TYPE = "kSigmoid"

    def setup(self, src_shapes, batchsize):
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        return ops.sigmoid(inputs[0])


class DropoutLayer(Layer):
    """kDropout (reference: layer.cc:126-160)."""

    TYPE = "kDropout"

    def setup(self, src_shapes, batchsize):
        self.pdrop = (
            self.cfg.dropout_param.dropout_ratio
            if self.cfg.dropout_param
            else 0.5
        )
        return require_one_src(self, src_shapes)

    def apply(self, params, inputs, *, training, rng=None):
        if not training:
            return inputs[0]
        if rng is None:
            raise ValueError(f"dropout layer {self.name!r} needs an rng key")
        return ops.dropout(rng, inputs[0], self.pdrop, training)


class LRNLayer(Layer):
    """kLRN (reference: layer.cc:331-378). ACROSS_CHANNELS only, like the
    reference implementation."""

    TYPE = "kLRN"

    def setup(self, src_shapes, batchsize):
        p = self.cfg.lrn_param
        self.local_size = p.local_size if p else 5
        if self.local_size % 2 != 1:
            raise ConfigError(f"layer {self.name!r}: LRN local_size must be odd")
        self.alpha = p.alpha if p else 1.0
        self.beta = p.beta if p else 0.75
        self.knorm = p.knorm if p else 1.0
        src = require_one_src(self, src_shapes)
        if len(src) != 4:
            raise ConfigError(f"layer {self.name!r}: LRN needs NCHW input")
        return src

    def apply(self, params, inputs, *, training, rng=None):
        return ops.lrn(
            inputs[0],
            local_size=self.local_size,
            alpha=self.alpha,
            beta=self.beta,
            knorm=self.knorm,
        )


class PoolingLayer(Layer):
    """kPooling (reference: layer.cc:476-540), ceil-mode shape arithmetic."""

    TYPE = "kPooling"

    def setup(self, src_shapes, batchsize):
        p = self.cfg.pooling_param
        if p is None or not p.kernel:
            raise ConfigError(f"layer {self.name!r}: pooling_param.kernel required")
        self.kernel, self.stride, self.pool = p.kernel, p.stride, p.pool
        src = require_one_src(self, src_shapes)
        if len(src) == 3:
            n, h, w = src
            c = 1
            self._expand = True
        elif len(src) == 4:
            n, c, h, w = src
            self._expand = False
        else:
            raise ConfigError(f"layer {self.name!r}: pooling needs 3/4-D input")
        ph = ops.pooled_size(h, self.kernel, self.stride)
        pw = ops.pooled_size(w, self.kernel, self.stride)
        return (n, c, ph, pw)

    def apply(self, params, inputs, *, training, rng=None):
        x = inputs[0]
        if x.ndim == 3:
            x = x[:, None]
        fn = ops.max_pool2d if self.pool == "MAX" else ops.avg_pool2d
        return fn(x, self.kernel, self.stride)
