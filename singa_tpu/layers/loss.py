"""Loss layers (reference: SoftmaxLossLayer, src/worker/layer.cc:704-764)."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .. import ops
from ..config.schema import ConfigError
from .base import Layer, Shape, feature_dim


class SoftmaxLossLayer(Layer):
    """kSoftmaxLoss: softmax + cross-entropy + top-k precision.

    Takes two srclayers (logits, label). apply returns (loss, metrics); the
    graph accumulates the loss term and the trainer averages metrics like
    the reference's Performance class (worker.cc:350-386). Refuses
    kLayerPartition like the reference (layer.h:216-221).
    """

    TYPE = "kSoftmaxLoss"
    is_losslayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        if len(src_shapes) != 2:
            raise ConfigError(
                f"layer {self.name!r}: kSoftmaxLoss needs (logits, label) "
                f"srclayers, got {len(src_shapes)}"
            )
        if self.cfg.partition_type == "kLayerPartition":
            raise ConfigError(
                f"layer {self.name!r}: kSoftmaxLoss cannot be layer-partitioned"
            )
        if self.partition_type == "kLayerPartition":
            # net-level kLayerPartition downgrades to kNone here, like the
            # reference forcing the loss layer out of the neuron split
            # (layer.h:216-221)
            self.partition_type = "kNone"
        p = self.cfg.softmaxloss_param
        self.topk = p.topk if p else 1
        self.scale = p.scale if p else 1.0
        return src_shapes[0]

    def apply(self, params, inputs, *, training, rng=None):
        logits, labels = inputs
        return ops.softmax_loss(
            logits, labels, topk=self.topk, scale=self.scale
        )


class EuclideanLossLayer(Layer):
    """kEuclideanLoss: 0.5 * mean squared reconstruction error.

    singa-tpu extension (no counterpart in this reference snapshot): the
    regression/autoencoder loss needed by BASELINE config 4's deep
    autoencoder, where the target srclayer is the input image itself.
    Takes (prediction, target) srclayers; both are flattened to
    (batch, -1). loss = 0.5/batch * sum((pred - target)^2).
    """

    TYPE = "kEuclideanLoss"
    is_losslayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        if len(src_shapes) != 2:
            raise ConfigError(
                f"layer {self.name!r}: kEuclideanLoss needs (prediction, "
                f"target) srclayers, got {len(src_shapes)}"
            )
        pdim = feature_dim(src_shapes[0])
        tdim = feature_dim(src_shapes[1])
        if pdim != tdim:
            raise ConfigError(
                f"layer {self.name!r}: prediction size {pdim} != target "
                f"size {tdim}"
            )
        return src_shapes[0]

    def apply(self, params, inputs, *, training, rng=None):
        # accumulate the reduction in fp32 even under bf16 compute
        pred = inputs[0].reshape(inputs[0].shape[0], -1).astype(jnp.float32)
        target = inputs[1].reshape(inputs[1].shape[0], -1).astype(jnp.float32)
        loss = 0.5 * jnp.mean(jnp.sum(jnp.square(pred - target), axis=1))
        return loss, {"loss": loss}
