"""Layer registry.

String-keyed factory mirroring the reference's Factory<Layer> +
NeuralNet::RegistryLayers 18 built-ins (src/worker/neuralnet.cc:13-33,
include/utils/factory.h:22-56). ``register_layer`` lets user code add types,
like the reference's factory Register calls.
"""

from __future__ import annotations

from ..config.schema import ConfigError, LayerConfig
from .base import Layer
from .connector import (
    BridgeDstLayer,
    BridgeSrcLayer,
    ConcateLayer,
    SliceLayer,
    SplitLayer,
)
from .data import (
    LabelLayer,
    LMDBDataLayer,
    MnistImageLayer,
    RGBImageLayer,
    ShardDataLayer,
)
from .loss import EuclideanLossLayer, SoftmaxLossLayer
from .norm import AddLayer, BatchNormLayer, GlobalPoolingLayer
from .rbm import RBMLayer
from .sequence import (
    AttentionLayer,
    DenseLayer,
    EmbeddingLayer,
    LayerNormLayer,
    LMLossLayer,
    MoELayer,
    SequenceDataLayer,
)
from .neuron import (
    ConvolutionLayer,
    DropoutLayer,
    InnerProductLayer,
    LRNLayer,
    PoolingLayer,
    ReLULayer,
    SigmoidLayer,
    TanhLayer,
)

_REGISTRY: dict[str, type[Layer]] = {}


def register_layer(cls: type[Layer]) -> type[Layer]:
    if not cls.TYPE:
        raise ValueError(f"{cls.__name__} has no TYPE")
    _REGISTRY[cls.TYPE] = cls
    return cls


def create_layer(cfg: LayerConfig, net_partition: str = "kNone") -> Layer:
    try:
        cls = _REGISTRY[cfg.type]
    except KeyError:
        raise ConfigError(
            f"unknown layer type {cfg.type!r} (registered: {sorted(_REGISTRY)})"
        ) from None
    return cls(cfg, net_partition)


def registered_types() -> list[str]:
    return sorted(_REGISTRY)


# the reference's 18 built-ins (neuralnet.cc:13-33) + extensions:
# kSigmoid, kRBM + kEuclideanLoss (the CD/autoencoder path, BASELINE #4),
# kBatchNorm/kAdd/kGlobalPooling (the ResNet vocabulary, BASELINE #5),
# kSequenceData/kEmbedding/kLayerNorm/kAttention/kDense/kLMLoss/kMoE (the
# transformer-LM vocabulary — long-context + MoE as config citizens)
for _cls in (
    RBMLayer,
    EuclideanLossLayer,
    AddLayer,
    BatchNormLayer,
    GlobalPoolingLayer,
    SequenceDataLayer,
    EmbeddingLayer,
    LayerNormLayer,
    AttentionLayer,
    DenseLayer,
    MoELayer,
    LMLossLayer,
    ConvolutionLayer,
    ConcateLayer,
    DropoutLayer,
    InnerProductLayer,
    RGBImageLayer,
    LabelLayer,
    LMDBDataLayer,
    LRNLayer,
    MnistImageLayer,
    BridgeDstLayer,
    BridgeSrcLayer,
    PoolingLayer,
    ReLULayer,
    ShardDataLayer,
    SliceLayer,
    SoftmaxLossLayer,
    SplitLayer,
    TanhLayer,
    SigmoidLayer,
):
    register_layer(_cls)

__all__ = [
    "Layer",
    "create_layer",
    "register_layer",
    "registered_types",
]
