"""Normalization + residual layers (singa-tpu extensions).

The reference predates batch normalization and residual networks (its
layer registry tops out at LRN, src/worker/neuralnet.cc:13-33); these
layers extend the same config surface so BASELINE.md's stretch target —
ImageNet ResNet-50 (config 5) — is expressible as a plain job file.

kBatchNorm's running statistics are the framework's first *buffers*:
non-trainable state updated by the layer inside the jitted step and
carried between steps by the trainer (layers/base.py BufferSpec). Under a
data-sharded batch, GSPMD turns the batch-mean reductions into cross-chip
psums automatically — i.e. sync BatchNorm over the whole global batch,
with no BN-specific communication code.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError
from .base import Layer, Shape, require_one_src


class BatchNormLayer(Layer):
    """kBatchNorm: per-channel batch normalization (NCHW axis 1, or the
    feature axis of 2-D inputs).

    Training normalizes by batch statistics and folds them into running
    stats with Caffe's momentum convention
    (running = momentum * running + (1 - momentum) * batch);
    eval normalizes by the running stats.
    """

    TYPE = "kBatchNorm"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.batchnorm_param
        self.momentum = p.momentum if p else 0.9
        self.eps = p.eps if p else 1e-5
        self.stats_stride = p.stats_sample_stride if p else 1
        if self.stats_stride < 1:
            raise ConfigError(
                f"layer {self.name!r}: stats_sample_stride must be >= 1"
            )
        # leave at least 8 sample rows in the stats subsample: a stride
        # that reduces stats to 1-2 rows drives per-channel variance
        # toward 0 and inv toward rsqrt(eps) ~ 316 — silent divergence,
        # not a perf knob
        if self.stats_stride > 1 and batchsize // self.stats_stride < 8:
            raise ConfigError(
                f"layer {self.name!r}: stats_sample_stride "
                f"{self.stats_stride} leaves "
                f"{max(batchsize // self.stats_stride, 0)} of {batchsize} "
                "sample rows for the batch moments (need >= 8)"
            )
        src = require_one_src(self, src_shapes)
        if len(src) not in (2, 4):
            raise ConfigError(
                f"layer {self.name!r}: kBatchNorm needs (N,C,H,W) or (N,F) "
                f"input, got {src}"
            )
        c = src[1]
        self.gname = self._declare_param(
            0, "gamma", (c,), neuron_axis=0
        )
        self.bname = self._declare_param(1, "beta", (c,), neuron_axis=0)
        self.mean_buf = self._declare_buffer("running_mean", (c,), 0.0)
        self.var_buf = self._declare_buffer("running_var", (c,), 1.0)
        return src

    def apply_stateful(self, params, buffers, inputs, *, training, rng=None):
        from .. import ops

        x = inputs[0]
        if training:
            # running mean anchors the one-pass moments: a free
            # independent input (an anchor computed from x costs
            # ~2.5ms/step on ResNet-50 — ops/norm.py docstring)
            anchor = jax.lax.stop_gradient(buffers[self.mean_buf])
            if self.stats_stride > 1:
                # OPT-IN subsample-stats + straight-through backward
                # (different math; ops/norm.py batch_norm_train_sampled)
                y, mean, var = ops.batch_norm_train_sampled(
                    x,
                    params[self.gname],
                    params[self.bname],
                    self.eps,
                    self.stats_stride,
                    shift=anchor,
                )
            else:
                # fused one-pass BN (ops/norm.py custom VJP — stats in
                # fp32, minimal HBM traffic; BASELINE.md r4 ablation)
                y, mean, var = ops.batch_norm_train(
                    x,
                    params[self.gname],
                    params[self.bname],
                    self.eps,
                    shift=anchor,
                )
            # running stats are a detached side effect
            mean = jax.lax.stop_gradient(mean)
            var = jax.lax.stop_gradient(var)
            m = self.momentum
            updates = {
                self.mean_buf: m * buffers[self.mean_buf] + (1 - m) * mean,
                self.var_buf: m * buffers[self.var_buf] + (1 - m) * var,
            }
            return y, updates
        y = ops.batch_norm_infer(
            x,
            params[self.gname],
            params[self.bname],
            buffers[self.mean_buf],
            buffers[self.var_buf],
            self.eps,
        )
        return y, {}

    def apply(self, params, inputs, *, training, rng=None):
        raise RuntimeError(
            f"layer {self.name!r}: kBatchNorm is stateful; the net must "
            "call apply_stateful (buffers plumbing)"
        )


class AddLayer(Layer):
    """kAdd: elementwise sum of all srclayers — the residual connection.
    Shapes must match exactly (use a projection conv on the shortcut when
    they don't, like standard ResNet type-B shortcuts)."""

    TYPE = "kAdd"
    decode_positionwise = True  # elementwise: serving decode reuses apply

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        if len(src_shapes) < 2:
            raise ConfigError(
                f"layer {self.name!r}: kAdd needs >= 2 srclayers"
            )
        first = src_shapes[0]
        for s in src_shapes[1:]:
            if tuple(s) != tuple(first):
                raise ConfigError(
                    f"layer {self.name!r}: kAdd shape mismatch {first} vs {s}"
                )
        return first

    def apply(self, params, inputs, *, training, rng=None):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return out


class GlobalPoolingLayer(Layer):
    """kGlobalPooling: mean (AVE, default) or max over the spatial dims of
    an NCHW input -> (N, C)."""

    TYPE = "kGlobalPooling"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.globalpooling_param
        self.pool = p.pool if p else "AVE"
        src = require_one_src(self, src_shapes)
        if len(src) != 4:
            raise ConfigError(
                f"layer {self.name!r}: kGlobalPooling needs NCHW input"
            )
        return (src[0], src[1])

    def apply(self, params, inputs, *, training, rng=None):
        x = inputs[0]
        if self.pool == "MAX":
            return jnp.max(x, axis=(2, 3))
        return jnp.mean(x, axis=(2, 3))
