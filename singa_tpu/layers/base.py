"""Layer abstraction.

The reference's Layer (include/worker/base_layer.h:38-258) is a stateful
object with Setup/ComputeFeature/ComputeGradient over owned blobs. Here a
layer is *static metadata + a pure function*: ``setup`` runs shape inference
and declares param specs once at graph-build time; ``apply`` is traced into
the single jitted train step, so there is no ComputeGradient — jax autodiff
provides it. Partition metadata (partition_dimension, connection_type,
base_layer.h:121-140) is kept so the parallel package can map it to GSPMD
shardings.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError, LayerConfig
from ..params import ParamSpec

Shape = tuple[int, ...]


class BufferSpec:
    """Non-trainable per-layer state (e.g. batch-norm running stats):
    initialized to a fill value, updated by the layer's own rule inside
    the step (never by the updater), checkpointed alongside params."""

    __slots__ = ("name", "shape", "init")

    def __init__(self, name: str, shape: Shape, init: float):
        self.name = name
        self.shape = tuple(shape)
        self.init = init


class Layer:
    """Base class; subclasses set TYPE and override setup/apply."""

    TYPE: str = ""
    # partition_dimension(): 0 = batch (kDataPartition), 1 = neuron
    # (kLayerPartition), -1 = unpartitionable (base_layer.h:121-128)
    PARTITION_DIM_FOR = {"kDataPartition": 0, "kLayerPartition": 1, "kNone": -1}
    # connection_type(): kOneToOne (elementwise) unless overridden
    CONNECTION = "kOneToOne"

    is_datalayer = False
    is_parserlayer = False
    is_losslayer = False
    is_connectorlayer = False
    #: layer's apply returns (out, aux_loss); Net.forward adds
    #: layer.aux_weight * aux_loss to the total (kMoE load balancing)
    has_aux_loss = False
    #: position-wise over a (B, S, ...) sequence dim: ``apply`` on a
    #: Q-token suffix equals the full-sequence apply restricted to those
    #: positions, so the serving-tier incremental decode
    #: (serve/conf_decode.py) can reuse ``apply`` unchanged. Layers with
    #: cross-position state instead implement ``decode_step`` (kAttention
    #: caches K/V, kEmbedding needs absolute positions).
    decode_positionwise = False

    def __init__(self, cfg: LayerConfig, net_partition: str = "kNone"):
        self.cfg = cfg
        self.name = cfg.name
        self.srclayers: list[str] = list(cfg.srclayers)
        self.partition_type = cfg.partition_type or net_partition
        self.out_shape: Shape | None = None
        self._param_specs: dict[str, ParamSpec] = {}
        self._buffer_specs: dict[str, BufferSpec] = {}
        #: device mesh, bound by the trainer (Net.bind_mesh) — static
        #: metadata for layers whose compute is mesh-aware (ring
        #: attention's seq axis, kMoE's expert axis); None = single-device
        self.mesh = None

    # ---------------- build time ----------------

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        """Infer the output shape and declare params; returns out_shape."""
        raise NotImplementedError

    def validate(self, src_layers: Sequence["Layer"]) -> None:
        """Optional cross-layer check, called by Net.setup with the
        actual source layer objects after this layer's setup (shape
        inference alone can't see e.g. a data layer's value range)."""

    def param_specs(self) -> dict[str, ParamSpec]:
        """Qualified-name -> spec, declared during setup."""
        return self._param_specs

    def _declare_param(
        self,
        idx: int,
        default_name: str,
        shape: Shape,
        fan_in: int = 0,
        neuron_axis: int | None = None,
        expert_axis: int | None = None,
    ) -> str:
        """Register param ``<layer>/<name>`` from cfg.param[idx] (if given)."""
        cfg = self.cfg.param[idx] if idx < len(self.cfg.param) else None
        pname = (cfg.name if cfg and cfg.name else default_name)
        qualified = f"{self.name}/{pname}"
        share = list(self.cfg.share_param)
        owner = share[idx] if idx < len(share) else None
        self._param_specs[qualified] = ParamSpec.from_config(
            cfg,
            qualified,
            tuple(shape),
            fan_in=fan_in,
            owner=owner,
            neuron_axis=neuron_axis,
            expert_axis=expert_axis,
        )
        return qualified

    def buffer_specs(self) -> dict[str, BufferSpec]:
        return self._buffer_specs

    def _declare_buffer(
        self, default_name: str, shape: Shape, init: float = 0.0
    ) -> str:
        qualified = f"{self.name}/{default_name}"
        self._buffer_specs[qualified] = BufferSpec(qualified, shape, init)
        return qualified

    @property
    def has_buffers(self) -> bool:
        return bool(self._buffer_specs)

    @property
    def partition_dim(self) -> int:
        return self.PARTITION_DIM_FOR[self.partition_type]

    # ---------------- trace time ----------------

    def apply(
        self,
        params: dict[str, jnp.ndarray],
        inputs: list[Any],
        *,
        training: bool,
        rng: jax.Array | None = None,
    ) -> Any:
        """Pure forward; traced inside the jitted step."""
        raise NotImplementedError

    def apply_stateful(
        self,
        params: dict[str, jnp.ndarray],
        buffers: dict[str, jnp.ndarray],
        inputs: list[Any],
        *,
        training: bool,
        rng: jax.Array | None = None,
    ) -> tuple[Any, dict[str, jnp.ndarray]]:
        """Forward for layers with buffers: returns (out, buffer updates).
        Only called when ``has_buffers``."""
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name!r}, out={self.out_shape})"


def feature_dim(shape: Shape) -> int:
    """Product of the non-batch dims — the reference's flatten-to-(batch,
    vdim) convention used by FC/RBM/loss layers (layer.cc:171-176)."""
    out = 1
    for d in shape[1:]:
        out *= d
    return out


def require_one_src(layer: Layer, src_shapes: Sequence[Shape]) -> Shape:
    if len(src_shapes) != 1:
        raise ConfigError(
            f"layer {layer.name!r} ({layer.TYPE}) expects exactly one "
            f"srclayer, got {len(src_shapes)}"
        )
    return src_shapes[0]
