"""Sequence-modeling layers (singa-tpu extensions).

The reference predates sequence models entirely (SURVEY §5: no attention
op anywhere); these layers make byte/token language models expressible in
the same text-proto job surface as every other net, training through the
identical engine — device cache, scan chunks, bf16 compute, checkpoints.
The code-level transformer API (singa_tpu/models/transformer.py, with
ring attention for sequence parallelism) remains the power-user path;
this is the config-driven one.

Data flows as (B, S) int32 tokens from kSequenceData, through kEmbedding
-> (B, S, D), residual blocks built from kLayerNorm / kAttention /
kDense / kAdd, into kLMLoss (next-token cross-entropy).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError
from ..ops.attention import attention
from .base import Layer, Shape, require_one_src
from .data import _ArrayDataLayer


def load_token_arrays(path: str):
    """Decode a token shard: each record's uint8 ``pixel`` bytes are one
    fixed-length sequence (byte-level vocab), label unused. -> (tokens
    int32 (N, S), labels int32 (N,))."""
    from ..data.pipeline import load_shard_arrays

    images, labels = load_shard_arrays(path)
    if images.ndim != 2:
        raise ConfigError(
            f"token shard {path!r}: expected flat (N, S) sequences, got "
            f"shape {images.shape}"
        )
    return images.astype("int32"), labels


class SequenceDataLayer(_ArrayDataLayer):
    """kSequenceData: batches of fixed-length token sequences."""

    TYPE = "kSequenceData"
    LOADER = staticmethod(load_token_arrays)


class EmbeddingLayer(Layer):
    """kEmbedding: token + learned positional embedding."""

    TYPE = "kEmbedding"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.embedding_param
        if p is None:
            raise ConfigError(
                f"layer {self.name!r}: embedding_param required"
            )
        src = require_one_src(self, src_shapes)
        if len(src) != 2:
            raise ConfigError(
                f"layer {self.name!r}: expects (batch, seq) token input"
            )
        self.seq_len = src[1]
        self.vocab = p.vocab_size
        self.dim = p.embedding_dim
        max_len = p.max_len or self.seq_len
        if max_len < self.seq_len:
            raise ConfigError(
                f"layer {self.name!r}: max_len {max_len} < sequence "
                f"length {self.seq_len}"
            )
        self.tok = self._declare_param(
            0, "tok", (self.vocab, self.dim), fan_in=self.dim
        )
        self.pos = self._declare_param(
            1, "pos", (max_len, self.dim), fan_in=self.dim
        )
        return (src[0], self.seq_len, self.dim)

    def validate(self, src_layers) -> None:
        # JAX gather clamps out-of-range ids silently, so an undersized
        # vocab would train on garbage without this build-time check
        src = src_layers[0]
        if getattr(src, "is_datalayer", False) and hasattr(src, "images"):
            top = int(src.images.max())
            if top >= self.vocab:
                raise ConfigError(
                    f"layer {self.name!r}: vocab_size {self.vocab} <= max "
                    f"token id {top} in {src.name!r}'s data"
                )

    def apply(self, params, inputs, *, training, rng=None):
        tokens = inputs[0]["image"].astype(jnp.int32)
        s = tokens.shape[1]
        return params[self.tok][tokens] + params[self.pos][:s]

    def decode_step(self, params, tokens, pos):
        """Serving-tier incremental apply (serve/conf_decode.py): embed
        Q tokens at ABSOLUTE positions [pos, pos+Q) — apply()'s ``[:s]``
        positional slice assumes the window starts at 0, which is only
        true for the first decode chunk."""
        q_len = tokens.shape[1]
        p = jnp.minimum(
            pos + jnp.arange(q_len), params[self.pos].shape[0] - 1
        )
        return params[self.tok][tokens.astype(jnp.int32)] + params[self.pos][p]


class LayerNormLayer(Layer):
    """kLayerNorm over the last dim; stats in fp32 under bf16 compute."""

    TYPE = "kLayerNorm"
    decode_positionwise = True  # per-position stats: decode reuses apply

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.layernorm_param
        self.eps = p.eps if p else 1e-5
        src = require_one_src(self, src_shapes)
        d = src[-1]
        self.scale = self._declare_param(0, "scale", (d,))
        self.bias = self._declare_param(1, "bias", (d,))
        return src

    def apply(self, params, inputs, *, training, rng=None):
        x = inputs[0]
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        return (
            y.astype(x.dtype) * params[self.scale] + params[self.bias]
        ).astype(x.dtype)


class AttentionLayer(Layer):
    """kAttention: causal multi-head self-attention with fused QKV."""

    TYPE = "kAttention"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.attention_param
        if p is None:
            raise ConfigError(
                f"layer {self.name!r}: attention_param required"
            )
        src = require_one_src(self, src_shapes)
        if len(src) != 3:
            raise ConfigError(
                f"layer {self.name!r}: expects (batch, seq, dim) input"
            )
        d = src[-1]
        self.heads = p.num_heads
        if d % self.heads:
            raise ConfigError(
                f"layer {self.name!r}: dim {d} not divisible by "
                f"num_heads {self.heads}"
            )
        self.mode = p.mode
        self.qkv = self._declare_param(
            0, "qkv", (d, 3 * d), fan_in=d, neuron_axis=1
        )
        self.out = self._declare_param(
            1, "out", (d, d), fan_in=d, neuron_axis=0
        )
        return src

    def _seq_mesh(self):
        """The bound mesh, when it carries a >1-wide seq axis."""
        mesh = self.mesh
        if mesh is not None and dict(mesh.shape).get("seq", 1) > 1:
            return mesh
        return None

    def apply(self, params, inputs, *, training, rng=None):
        x = inputs[0]
        b, s, d = x.shape
        w = params[self.qkv]
        qkv = (x.astype(w.dtype) @ w).reshape(
            b, s, 3, self.heads, d // self.heads
        )
        q, k, v = (jnp.moveaxis(qkv[:, :, j], 2, 1) for j in range(3))
        if self.mode == "ring" and self._seq_mesh() is not None:
            # sequence parallelism: K/V shards rotate the seq mesh axis
            # (parallel/ring.py); with no seq axis the mode degrades to
            # flash below — same math, single shard
            from ..parallel.ring import ring_attention

            o = ring_attention(q, k, v, self._seq_mesh(), causal=True)
        elif self.mode in ("flash", "ring"):
            # dense-vs-kernel by per-device score footprint (see
            # ops.attention.auto_attention — dense measured faster
            # whenever the scores fit; the kernel is for long context)
            from ..ops.attention import auto_attention

            # footprint divisor: only axes that actually shard the
            # (B, H, S, S) score tensor — batch over data, seq over seq,
            # heads over model. Pipe/expert axes REPLICATE attention
            # compute, so counting them (mesh.size) would underestimate
            # the per-device footprint and pick dense attention in
            # regimes where the scores exceed per-device HBM.
            n_dev = 1
            if self.mesh is not None:
                for axis in ("data", "seq", "model"):
                    n_dev *= self.mesh.shape.get(axis, 1)
            o = auto_attention(q, k, v, causal=True, n_devices=n_dev)
        else:
            o = attention(q, k, v, causal=True)
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, d)
        return o.astype(w.dtype) @ params[self.out]

    def decode_step(self, params, x, cache, pos):
        """Serving-tier incremental apply: Q new positions at
        [pos, pos+Q) write their K/V into the (B, H, C, D) caches and
        attend the whole masked cache via the SAME ``cache_attend`` body
        the code-API engine runs (models/transformer.py) — the flash /
        ring training modes are score-footprint optimizations the
        chunked cache path does not need. -> (out, (new_k, new_v))."""
        from ..models.transformer import cache_attend

        b, q_len, d = x.shape
        w = params[self.qkv]
        qkv = (x.astype(w.dtype) @ w).reshape(
            b, q_len, 3, self.heads, d // self.heads
        )
        q, k, v = (jnp.moveaxis(qkv[:, :, j], 2, 1) for j in range(3))
        kc, vc = cache
        kc = jax.lax.dynamic_update_slice_in_dim(
            kc, k.astype(kc.dtype), pos, axis=2
        )
        vc = jax.lax.dynamic_update_slice_in_dim(
            vc, v.astype(vc.dtype), pos, axis=2
        )
        positions = jnp.broadcast_to(
            pos + jnp.arange(q_len)[None, :], (b, q_len)
        )
        o = cache_attend(q, kc, vc, positions)
        o = jnp.moveaxis(o, 1, 2).reshape(b, q_len, d)
        return o.astype(w.dtype) @ params[self.out], (kc, vc)


class DenseLayer(Layer):
    """kDense: per-position linear map over the last dim (+ optional
    fused activation). Contrast kInnerProduct, which flattens."""

    TYPE = "kDense"
    decode_positionwise = True  # per-position map: decode reuses apply

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.dense_param
        if p is None:
            raise ConfigError(f"layer {self.name!r}: dense_param required")
        src = require_one_src(self, src_shapes)
        d = src[-1]
        self.hdim = p.num_output
        self.activation = p.activation
        self.w = self._declare_param(
            0, "weight", (d, self.hdim), fan_in=d, neuron_axis=1
        )
        self.bias_term = p.bias_term
        if self.bias_term:
            self.b = self._declare_param(1, "bias", (self.hdim,))
        return (*src[:-1], self.hdim)

    def apply(self, params, inputs, *, training, rng=None):
        w = params[self.w]
        out = inputs[0].astype(w.dtype) @ w
        if self.bias_term:
            out = out + params[self.b]
        if self.activation == "gelu":
            out = jax.nn.gelu(out)
        elif self.activation == "relu":
            out = jax.nn.relu(out)
        return out


class MoELayer(Layer):
    """kMoE: Switch-style top-1 mixture-of-experts FFN (singa-tpu
    extension — the reference predates MoE entirely).

    Expert weights carry expert_axis metadata, so param_shardings splits
    them over the cluster's expert mesh axis (nexperts_per_group); the
    compute then runs expert-parallel through parallel/moe.py's
    shard_map (local dispatch -> local experts -> psum combine). On a
    mesh without an expert axis the dense single-device path runs — the
    same math. The Switch load-balancing aux loss rides Net.forward's
    aux-loss channel with weight moe_param.aux_loss_weight."""

    TYPE = "kMoE"
    has_aux_loss = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.moe_param
        if p is None:
            raise ConfigError(f"layer {self.name!r}: moe_param required")
        src = require_one_src(self, src_shapes)
        if len(src) != 3:
            raise ConfigError(
                f"layer {self.name!r}: expects (batch, seq, dim) input"
            )
        d = src[-1]
        self.n_experts = p.num_experts
        self.d_ff = p.d_ff
        self.capacity_factor = p.capacity_factor
        self.aux_weight = p.aux_loss_weight
        self.dispatch = p.dispatch
        if self.dispatch not in ("psum", "alltoall"):
            raise ConfigError(
                f"layer {self.name!r}: moe_param.dispatch must be "
                f"'psum' or 'alltoall', got {self.dispatch!r}"
            )
        self.gate = self._declare_param(0, "gate", (d, self.n_experts),
                                        fan_in=d)
        self.up = self._declare_param(
            1, "up", (self.n_experts, d, self.d_ff),
            fan_in=d, expert_axis=0,
        )
        self.down = self._declare_param(
            2, "down", (self.n_experts, self.d_ff, d),
            fan_in=self.d_ff, expert_axis=0,
        )
        return src

    def _expert_mesh(self):
        mesh = self.mesh
        if mesh is not None and dict(mesh.shape).get("expert", 1) > 1:
            return mesh
        return None

    def apply(self, params, inputs, *, training, rng=None):
        from ..parallel.moe import moe_ffn, moe_ffn_a2a, moe_ffn_dense

        x = inputs[0]
        p = {
            "gate": params[self.gate],
            "up": params[self.up],
            "down": params[self.down],
        }
        mesh = self._expert_mesh()
        if mesh is not None:
            nexp = dict(mesh.shape)["expert"]
            if self.n_experts % nexp:
                raise ConfigError(
                    f"layer {self.name!r}: num_experts {self.n_experts} "
                    f"not divisible by expert axis width {nexp}"
                )
            ndata = dict(mesh.shape).get("data", 1)
            if self.dispatch == "alltoall":
                if x.shape[0] % (ndata * nexp):
                    raise ConfigError(
                        f"layer {self.name!r}: alltoall dispatch shards "
                        f"the batch over data x expert — batch "
                        f"{x.shape[0]} must be divisible by "
                        f"{ndata * nexp}"
                    )
                return moe_ffn_a2a(
                    x, p, mesh, capacity_factor=self.capacity_factor
                )
            return moe_ffn(
                x, p, mesh, capacity_factor=self.capacity_factor
            )
        return moe_ffn_dense(x, p, self.capacity_factor)


class LMLossLayer(Layer):
    """kLMLoss: next-token cross-entropy over (B, S, V) logits.

    srclayers: (logits, kSequenceData). Position t's logits predict token
    t+1; the final position is dropped. Metrics: loss (mean NLL) and
    precision (next-token top-1 accuracy), averaged by Performance like
    every loss layer."""

    TYPE = "kLMLoss"
    is_losslayer = True

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        if len(src_shapes) != 2:
            raise ConfigError(
                f"layer {self.name!r}: kLMLoss needs (logits, tokens) "
                f"srclayers, got {len(src_shapes)}"
            )
        if len(src_shapes[0]) != 3:
            raise ConfigError(
                f"layer {self.name!r}: logits must be (batch, seq, vocab)"
            )
        return src_shapes[0]

    def apply(self, params, inputs, *, training, rng=None):
        logits, feed = inputs
        tokens = feed["image"].astype(jnp.int32)
        logp = jax.nn.log_softmax(
            logits[:, :-1].astype(jnp.float32), axis=-1
        )
        targets = tokens[:, 1:]
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll)
        hit = jnp.argmax(logp, axis=-1) == targets
        return loss, {
            "loss": loss,
            "precision": jnp.mean(hit.astype(jnp.float32)),
        }
