"""Restricted Boltzmann machine layer (the contrastive-divergence path).

The reference *declares* CD training — GradCalcAlg.kContrastiveDivergence
(src/proto/model.proto:40-44) and the TrainOneBatch comment naming a
"CD worker" (include/worker/base_layer.h:96-97) — but this snapshot ships
no RBM layer or CD worker; BASELINE config 4 ("RBM / deep autoencoder on
MNIST") makes it a target anyway. This layer is that greenfield fill,
designed TPU-first: the whole CD-k Gibbs chain is a fixed-length
`lax.scan`-free unroll of sigmoid+matmul ops inside the jitted step, so
the MXU sees (B,V)x(V,H) matmuls and XLA fuses the sampling elementwise.

In a kBackPropagation net (or at eval time) the layer acts as a plain
feature extractor: apply() returns the mean-field hidden probabilities,
which is what lets stacked RBMs form the encoder of a deep autoencoder
(pretrain with alg: kContrastiveDivergence, then kPretrained-init the
unrolled MLP — the classic deep-autoencoder recipe).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError
from .base import Layer, Shape, feature_dim, require_one_src


class RBMLayer(Layer):
    """kRBM: binary-binary RBM with weight (V,H), vbias (V), hbias (H)."""

    TYPE = "kRBM"
    CONNECTION = "kOneToAll"

    def setup(self, src_shapes: Sequence[Shape], batchsize: int) -> Shape:
        p = self.cfg.rbm_param
        if p is None or not p.num_hidden:
            raise ConfigError(
                f"layer {self.name!r}: rbm_param.num_hidden required"
            )
        src = require_one_src(self, src_shapes)
        vdim = feature_dim(src)
        self.vdim, self.hdim = vdim, p.num_hidden
        self.cd_k = max(1, p.cd_k)
        self.sample_visible = p.sample_visible
        self.wname = self._declare_param(
            0,
            "weight",
            (vdim, self.hdim),
            fan_in=vdim * self.hdim,  # the FC convention (layer.cc:178)
            neuron_axis=1,
        )
        self.vbname = self._declare_param(1, "vbias", (vdim,))
        self.hbname = self._declare_param(
            2, "hbias", (self.hdim,), neuron_axis=0
        )
        return (src[0], self.hdim)

    # ---------------- mean-field propagation ----------------

    def _flat(self, v: jnp.ndarray) -> jnp.ndarray:
        return v.reshape(v.shape[0], -1)

    def prop_up(self, params, v: jnp.ndarray) -> jnp.ndarray:
        """P(h=1|v) = sigmoid(vW + hbias)."""
        return jax.nn.sigmoid(
            self._flat(v) @ params[self.wname] + params[self.hbname]
        )

    def prop_down(self, params, h: jnp.ndarray) -> jnp.ndarray:
        """P(v=1|h) = sigmoid(hW^T + vbias)."""
        return jax.nn.sigmoid(
            h @ params[self.wname].T + params[self.vbname]
        )

    def apply(self, params, inputs, *, training, rng=None):
        """Feature-extractor view: mean hidden probabilities."""
        return self.prop_up(params, inputs[0])

    # ---------------- contrastive divergence ----------------

    def cd_grads(self, params, v0, rng):
        """One CD-k estimate; returns (grads, metrics).

        Standard Hinton recipe: hidden states are *sampled* while driving
        the chain, the final hidden uses probabilities, the positive phase
        uses h0 probabilities, and grads are descent-oriented
        (neg - pos)/batch so the existing updaters (which subtract) ascend
        the log-likelihood.
        """
        v0 = self._flat(v0)
        batch = v0.shape[0]
        h0p = self.prop_up(params, v0)
        hk = jax.random.bernoulli(
            jax.random.fold_in(rng, 0), h0p
        ).astype(v0.dtype)
        vkp = v0
        for k in range(self.cd_k):
            vkp = self.prop_down(params, hk)
            vk = (
                jax.random.bernoulli(
                    jax.random.fold_in(rng, 2 * k + 1), vkp
                ).astype(v0.dtype)
                if self.sample_visible
                else vkp
            )
            hkp = self.prop_up(params, vk)
            hk = jax.random.bernoulli(
                jax.random.fold_in(rng, 2 * k + 2), hkp
            ).astype(v0.dtype)
        # negative-phase statistics from probabilities (lower variance, per
        # Hinton's practical guide), positive phase from the data
        grads = {
            self.wname: (vkp.T @ hkp - v0.T @ h0p) / batch,
            self.vbname: jnp.mean(vkp - v0, axis=0),
            self.hbname: jnp.mean(hkp - h0p, axis=0),
        }
        recon = jnp.mean(jnp.square(v0 - vkp))
        return grads, {"loss": recon}

    def recon_error(self, params, v):
        """Eval metric: one mean-field reconstruction pass."""
        v = self._flat(v)
        vp = self.prop_down(params, self.prop_up(params, v))
        return jnp.mean(jnp.square(v - vp))
