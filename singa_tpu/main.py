"""CLI entry point: ``python -m singa_tpu.main -model_conf F -cluster_conf F``.

Mirrors the reference binary's gflags surface (src/main.cc:13-18:
-procsID, -hostfile, -cluster_conf, -model_conf) so reference job launch
lines work unchanged. The worker/server role dispatch (main.cc:49-55)
disappears for TRAINING: there is no parameter-server tier — every
process is a trainer and grad sync is an XLA collective.
-procsID/-hostfile feed jax.distributed.initialize (parallel/launch.py)
when a multi-host run is launched reference-style; on TPU pods the
runtime's own environment drives the rendezvous and both flags may be
omitted.

The rank-picks-role pattern returns at SERVING scale: a ``fleet { ... }``
config block dispatches this process to a serving-fleet host instead
(singa_tpu/serve/fleet/) — ``-procsID`` picks its prefill/decode/unified
role exactly as main.cc:49-55 picked Worker vs Server, hosts exchange
paged-KV block migrations through a shared filesystem mailbox (no
jax.distributed rendezvous), and a SIGTERM'd host drains its in-flight
sequences to a PEER and exits 75.

Jobs run under the resilience supervisor (singa_tpu/resilience/): a
``resilience { ... }`` config block enables supervised auto-resume from
the newest complete checkpoint, SIGTERM/SIGINT drain with a resumable
exit status (75), the divergence guard, and the step watchdog. The
``-faults`` flag (or SINGA_TPU_FAULTS) injects a deterministic fault
plan — ``crash@7,sigterm@12,nanloss@5`` — for recovery drills and CI.

Telemetry (singa_tpu/obs/) is always on for jobs with a workspace: each
rank appends structured lifecycle events and phase spans to
``<workspace>/events/rank_k.jsonl`` (flushed at display cadence — the
step path gains no syscalls or device syncs); ``python -m
singa_tpu.tools.trace <workspace>`` merges them into one
Perfetto-loadable trace.json. A ``profile@K:steps=N`` term in the fault
plan brackets steps K..K+N with a ``jax.profiler`` trace into
``<workspace>/xprof/``. The ``telemetry { ... }`` config block tunes or
disables all of it.
"""

from __future__ import annotations

import argparse
import os
import sys

from .config import load_cluster_config, load_model_config


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="singa_tpu", description=__doc__, add_help=True
    )
    ap.add_argument("-model_conf", required=True, help="ModelProto text file")
    ap.add_argument("-cluster_conf", default=None, help="ClusterProto text file")
    ap.add_argument("-procsID", type=int, default=0, help="process rank")
    ap.add_argument("-hostfile", default=None,
                    help="one host per line; line 0 hosts the rendezvous")
    ap.add_argument("-seed", type=int, default=0, help="init/dropout RNG seed")
    ap.add_argument(
        "-faults",
        default=os.environ.get("SINGA_TPU_FAULTS"),
        help="deterministic fault plan, e.g. 'crash@7,sigterm@12', or a "
        "'profile@20:steps=5' jax.profiler trigger "
        "(resilience/faults.py grammar; also via SINGA_TPU_FAULTS)",
    )
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    # honor an explicit JAX_PLATFORMS even on images whose sitecustomize
    # pre-pins an accelerator plugin (the env var alone is overridden
    # there) — e.g. JAX_PLATFORMS=cpu for local multi-process fleets
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    from .parallel import init_distributed

    args = parse_args(argv)
    model_cfg = load_model_config(args.model_conf)
    cluster_cfg = (
        load_cluster_config(args.cluster_conf) if args.cluster_conf else None
    )
    if getattr(model_cfg, "fleet", None) is not None:
        # the reference's rank-picks-role dispatch (main.cc:49-55), at
        # serving scale: a ``fleet {}`` block makes this process a
        # serving-fleet host — -procsID picks prefill/decode/unified
        # (serve/fleet/host.role_for_rank) and hosts share nothing but
        # the mailbox, so no jax.distributed rendezvous is started
        from .serve.fleet.host import run_from_conf

        return run_from_conf(
            model_cfg, cluster_cfg, procs_id=args.procsID, seed=args.seed,
            faults=args.faults,
        )
    init_distributed(args.procsID, args.hostfile)
    # persistent-compile warm start: repeat runs skip XLA recompilation
    # (cache dir from the cluster conf / workspace; SINGA_TPU_COMPILE_CACHE
    # overrides, "off" disables — utils/compile_cache.py)
    from .utils.compile_cache import setup_compile_cache

    setup_compile_cache(cluster_cfg)
    # every job routes through the supervisor: configs without a
    # resilience block (and no fault plan) take its transparent
    # single-attempt path; configs with one get auto-resume, preemption
    # drain (exit 75 = resumable), divergence guard, and the watchdog
    from .resilience import supervisor

    rc = supervisor.run(
        model_cfg, cluster_cfg, seed=args.seed, faults=args.faults
    )
    from .resilience.coord import process_count

    if rc != 0 and process_count() > 1:
        # a non-zero exit in a multi-process job leaves peers
        # mid-collective (a crash) or exiting in parallel (a
        # coordinated drain). jax's atexit distributed shutdown would
        # block on them — or, when the coordination service dies first,
        # abort THIS process with SIGABRT, destroying the exit code the
        # launcher keys its restart decision on. Flush and leave with
        # the real status instead.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
