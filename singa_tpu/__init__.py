"""singa-tpu: a TPU-native deep-learning framework.

A ground-up JAX/XLA/pjit re-design with the capabilities of early Apache
SINGA (the parameter-server-era C++ system): protobuf-text-configured
feed-forward nets, the full SGD-family updater/schedule vocabulary, a sharded
record-file data pipeline, and distributed training — except the execution
engine is one sharded, jit-compiled XLA program over a `jax.sharding.Mesh`
instead of mshadow kernels stitched together by a ZeroMQ parameter server.

Package map (reference layer in parens, see SURVEY.md):
  config/    text-proto job configs            (src/proto/*.proto, L8)
  ops/       JAX functional op vocabulary      (mshadow tensor_expr_ext, L0)
  layers/    layer registry & implementations  (src/worker/layer.cc, L2)
  graph/     net DAG build + shape inference   (src/worker/neuralnet.cc, L2)
  params/    param specs + 6 init methods      (src/utils/param.cc, L4)
  optim/     5 updaters x 6 LR schedules       (src/utils/updater.cc, L3)
  data/      shard files, parsers, prefetch    (src/utils/shard.cc, L1/L9)
  parallel/  mesh, shardings, collectives      (cluster/router/bridges, L7)
  trainer/   training loop, cadences, ckpt     (src/worker/worker.cc, L5)
  models/    model family builders             (examples/, L9)
  tools/     sweep, plots, partitioner, dot    (script/, batch.sh, L9)
  native/    C++ shard/record codec            (src/utils/shard.cc, L1)
  utils/     metrics, timers, graph viz        (L9)
"""

__version__ = "0.1.0"
