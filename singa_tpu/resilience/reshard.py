"""Elastic restore: reshard an N-process sharded checkpoint onto M ranks.

The sharded checkpoint format (trainer/sharded_ckpt.py) records, per
array, the GLOBAL shape, its PartitionSpec, and each saved device
shard's global index box. That makes a save self-describing enough to
restore onto a job that looks nothing like the one that wrote it: the
reference's Elastic-SGD protocol let worker groups join and leave a
running job (include/utils/param.h:18-175); this module is the
checkpoint-side half of that story — a drained N-rank job resumes on M
ranks, both up and down.

``Resharder.place`` is the workhorse. Per entry it takes two paths:

  direct    every local target device's index box exactly matches a
            saved shard box — shard bytes go straight to their device,
            no host ever holds the global array (the fast path; also
            what a SAME-topology resume always takes).
  reshard   the boxes changed (different process count regrouped them,
            a different mesh width re-sliced them, or both): each local
            target box is assembled on the host from the INTERSECTING
            saved pieces and placed on its own device. Streaming
            per-target-shard: at no point does any host materialize the
            whole checkpoint, and — unlike a naive global-assemble +
            ``device_put`` — every byte this process touches lands on a
            device it can address, so the path works across real
            process boundaries.

Exactness contract (the PR 4/7 bar at a new world size):

  - Restored GLOBAL values are bitwise the saved ones — params, ZeRO
    update-layout optimizer slots, chunk-sharded error-feedback
    residuals, guard counters: re-slicing moves bytes, never math.
  - Stream positions are CONSUMED-batch counts against the global
    stream (every rank advances the same global cursor; the device
    shardings slice each batch, not the stream), so they are
    world-size-invariant by construction: restoring the manifest's
    positions on M ranks replays and skips nothing.
  - Training-trajectory identity additionally needs the reduction
    geometry preserved: when the M-rank job hosts the SAME mesh axis
    widths (N hosts x k chips -> M hosts x N*k/M chips — the elastic
    TPU case), the continuation is loss-identical (tol 0, proven
    bitwise in tests/test_mp_resilience.py). Changing an axis WIDTH
    changes fp32 reduction grouping, which no resharder can undo;
    state still restores bitwise, the trajectory is tolerance-level.

``hostable``/``check_manifest`` are the admission check: a target mesh
that cannot host a manifest's specs (an axis the spec names that the
mesh vocabulary lacks, or a dim with fewer elements than the target
axis width wants shards — beyond even the pad/replicate fallback) is
rejected loudly here at restore time, and statically by netlint ELA001
through ``--cluster`` (the same predicate, SRV001/KRN002 discipline).

No jax at module import time: netlint calls ``hostable`` from a pure
config walk.
"""

from __future__ import annotations

import numpy as np


class ReshardError(ValueError):
    """The target mesh cannot host this checkpoint's arrays."""


# ---------------------------------------------------------------------------
# admission: can this mesh host that manifest?
# ---------------------------------------------------------------------------


def _spec_dim_axes(entry) -> list[str]:
    """Mesh axis names one PartitionSpec dim entry pins (JSON form:
    None, a name, or a list of names)."""
    if entry is None:
        return []
    if isinstance(entry, (list, tuple)):
        return [str(a) for a in entry]
    return [str(entry)]


def hostable(
    shape: tuple[int, ...] | list[int],
    spec: list | None,
    axis_widths: dict[str, int],
) -> str | None:
    """None when ``axis_widths`` can host a re-scatter of an entry saved
    with ``spec`` at global ``shape``; else the human-readable reason.

    Two rejections, mirrored statically by netlint ELA001:

      - the spec names a mesh axis the target vocabulary lacks — the
        manifest belongs to a different system (or is corrupt), and
        guessing a placement for it would be silent data motion;
      - a dim holds fewer elements than the named axes' combined target
        width wants shards: even the pad/replicate fallback
        (parallel/shardings.py SHD001) cannot give every shard a slice
        without inventing a layout the manifest never promised.

    Indivisible-but-coverable dims (dim % width != 0, dim >= width) are
    hostable — GSPMD's uneven trailing shard / the stored-padding
    machinery covers them, exactly as at first materialization.
    """
    if spec is None:
        return None  # host value / replicated: any mesh hosts it
    for d, (dim, entry) in enumerate(zip(tuple(shape), spec)):
        axes = _spec_dim_axes(entry)
        if not axes:
            continue
        unknown = [a for a in axes if a not in axis_widths]
        if unknown:
            return (
                f"dim {d} is sharded over mesh axis(es) "
                f"{', '.join(map(repr, unknown))} that the target mesh "
                f"lacks (axes: {sorted(axis_widths)})"
            )
        width = 1
        for a in axes:
            width *= max(1, int(axis_widths[a]))
        if width > 1 and dim < width:
            return (
                f"dim {d} has {dim} element(s) but the target width of "
                f"axis(es) {'*'.join(axes)} is {width} — more shards "
                "than elements, beyond even the pad/replicate fallback"
            )
    return None


def check_manifest(
    manifest: dict, axis_widths: dict[str, int]
) -> dict[str, str]:
    """{entry key: reason} for every manifest array the target mesh
    cannot host (empty dict = the whole checkpoint reshard-restores).
    The runtime half raises ReshardError on these; netlint ELA001 is
    the static mirror."""
    problems: dict[str, str] = {}
    for key, info in manifest.get("arrays", {}).items():
        reason = hostable(
            tuple(info.get("shape", ())), info.get("spec"), axis_widths
        )
        if reason is not None:
            problems[key] = reason
    return problems


def checkpoint_nprocs(path: str) -> int | None:
    """The process count a sharded checkpoint dir was written by (its
    manifest's ``nprocs``); None for npz checkpoints / unreadable
    manifests. The supervisor uses this to announce an elastic resume
    before the trainer rebuilds."""
    import json
    import os

    try:
        with open(os.path.join(path, "manifest.json")) as f:
            return int(json.load(f).get("nprocs", 1))
    except (OSError, ValueError, TypeError):
        return None


# ---------------------------------------------------------------------------
# the resharder
# ---------------------------------------------------------------------------


def _box_of(index, shape) -> np.ndarray:
    """(ndim, 2) [start, stop) box from a device's index tuple (the
    sharded_ckpt _idx_box convention, scalars -> [[0, 1]])."""
    box = []
    for sl, dim in zip(index, shape):
        box.append(
            [
                0 if sl.start is None else int(sl.start),
                dim if sl.stop is None else int(sl.stop),
            ]
        )
    if not box:
        box = [[0, 1]]
    return np.asarray(box, dtype=np.int64)


def _assemble_box(
    target_box: np.ndarray,
    pieces: list,
    shape: tuple[int, ...],
    dtype,
    load,
) -> np.ndarray:
    """Assemble ONE target shard box from the intersecting saved pieces
    — the streaming core: the largest host buffer this ever allocates
    is one target shard, not the global array (and certainly not the
    checkpoint). ``pieces`` is [(index, saved box)] — piece BYTES are
    pulled through ``load(index)`` only after the boxes actually
    overlap, so a sharded target never decompresses the parts of the
    array other processes own."""
    if not shape:  # scalar: any piece IS the value
        for i, _ in pieces:
            return np.asarray(load(i), dtype=dtype).reshape(())
        return np.zeros((), dtype=dtype)
    ndim = len(shape)
    tb = np.asarray(target_box[:ndim], dtype=np.int64)
    out = np.zeros(tuple(int(b - a) for a, b in tb), dtype=dtype)
    for i, sbox in pieces:
        sb = np.asarray(sbox[:ndim], dtype=np.int64)
        lo = np.maximum(tb[:, 0], sb[:, 0])
        hi = np.minimum(tb[:, 1], sb[:, 1])
        if np.any(lo >= hi):
            continue  # no overlap: the piece's bytes are never read
        dst = tuple(
            slice(int(a - t0), int(b - t0))
            for a, b, t0 in zip(lo, hi, tb[:, 0])
        )
        src = tuple(
            slice(int(a - s0), int(b - s0))
            for a, b, s0 in zip(lo, hi, sb[:, 0])
        )
        out[dst] = np.asarray(load(i)[src], dtype=dtype)
    return out


class Resharder:
    """Restore a ``ShardedCheckpoint`` onto ANY topology.

    ``axis_widths`` (the target mesh's {axis: width}) arms the
    admission check: construction raises ``ReshardError`` listing every
    entry the mesh cannot host — the loud runtime rejection netlint
    ELA001 mirrors statically. ``place`` then restores entry by entry,
    direct shard-to-device where boxes match, box-intersection
    re-slicing where they do not; ``resharded_keys`` records which
    entries took the re-slicing path so the caller can log ONE summary
    line instead of a warning per array."""

    def __init__(
        self,
        ckpt,
        axis_widths: dict[str, int] | None = None,
        *,
        log=None,
    ):
        self.ckpt = ckpt
        self.log = log
        #: entries restored through box re-slicing (vs shard-to-device)
        self.resharded_keys: list[str] = []
        if axis_widths is not None:
            problems = check_manifest(ckpt.manifest, axis_widths)
            if problems:
                lines = "; ".join(
                    f"{k}: {r}" for k, r in sorted(problems.items())
                )
                raise ReshardError(
                    f"checkpoint {ckpt.path!r} cannot be resharded onto "
                    f"a mesh with axis widths {axis_widths}: {lines} "
                    "(netlint ELA001 flags this statically)"
                )

    @property
    def saved_nprocs(self) -> int:
        return int(self.ckpt.manifest.get("nprocs", 1))

    def place(self, key: str, sharding, dtype=None):
        """Device-place manifest entry ``key`` under ``sharding``
        (cast to ``dtype`` when given). Never materializes more than
        one target shard on the host; works across process boundaries
        in both directions (every byte lands on an addressable
        device)."""
        import jax

        ck = self.ckpt
        info = ck.manifest["arrays"][key]
        shape = tuple(info["shape"])
        dtype = np.dtype(info["dtype"]) if dtype is None else np.dtype(dtype)
        raw = ck.pieces(key)  # [(npz file, entry name, box)]
        # piece bytes load lazily (npz members decompress on access)
        # and at most once each: the direct path touches only the
        # boxes THIS process's devices want, the reshard path only the
        # pieces that actually intersect a local target box — never
        # "every saved shard of the array, just in case"
        loaded: dict[int, np.ndarray] = {}

        def load(i: int) -> np.ndarray:
            if i not in loaded:
                z, entry, _ = raw[i]
                loaded[i] = z[entry]
            return loaded[i]

        ndim = max(1, len(shape))

        def box_key(box) -> bytes:
            return np.asarray(box[:ndim], dtype=np.int64).tobytes()

        saved_boxes = [
            (i, np.asarray(box)) for i, (_, _, box) in enumerate(raw)
        ]
        by_box = {box_key(box): i for i, box in saved_boxes}
        dev_map = sharding.addressable_devices_indices_map(shape)
        targets = []
        direct = []
        for dev, index in dev_map.items():
            tbox = _box_of(index, shape)
            i = by_box.get(box_key(tbox))
            direct.append(i is not None)
            targets.append((dev, tbox, i))
        if all(direct) and targets:
            arrays = [
                jax.device_put(
                    np.asarray(load(i)).astype(dtype, copy=False), dev
                )
                for dev, _, i in targets
            ]
        else:
            # the reshard path: one host assembly per UNIQUE target
            # box — devices sharing a box (a dim replicated over some
            # mesh axis) reuse the same buffer instead of each paying
            # a full assembly held alive simultaneously
            self.resharded_keys.append(key)
            assembled: dict[bytes, np.ndarray] = {}
            arrays = []
            for dev, tbox, _ in targets:
                kb = box_key(tbox)
                if kb not in assembled:
                    assembled[kb] = _assemble_box(
                        tbox, saved_boxes, shape, dtype, load
                    )
                arrays.append(jax.device_put(assembled[kb], dev))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays
        )

    def summary(self) -> str | None:
        """One human line describing what got re-sliced; None when the
        whole restore took the direct path."""
        if not self.resharded_keys:
            return None
        n = len(self.resharded_keys)
        preview = ", ".join(sorted(self.resharded_keys)[:4])
        more = "" if n <= 4 else f", +{n - 4} more"
        return (
            f"resharded {n} entr{'y' if n == 1 else 'ies'} from the "
            f"{self.saved_nprocs}-process layout ({preview}{more})"
        )


# ---------------------------------------------------------------------------
# in-process serving restore — the live-rollout staging path
# ---------------------------------------------------------------------------


def load_serving_params(
    path: str, init_params: dict, *, log=None
) -> tuple[dict, dict]:
    """Restore a trained save's PARAM tree onto an in-process serving
    host -> ``(params, info)``. This is what both boot-time checkpoint
    threading (``fleet/host.run_from_conf``) and the live-rollout
    controller's staging (``serve/rollout.py``) call: ANY save restores
    onto ANY serving topology —

      - a retention FOLDER resolves through its LATEST marker (newest
        complete save wins, torn tails skipped — resilience/retention);
      - an npz checkpoint overlays by flat param name (the kPretrained
        contract: absent names keep their init, shape mismatches raise);
      - a SHARDED checkpoint dir reshard-restores through
        ``Resharder.place`` onto the serving host's replicated device —
        a save written by N training processes lands here regardless
        of N, the PR 15 box-intersection path.

    ``init_params`` is the freshly-initialized tree (``init_lm``) whose
    names/shapes define what the serving engine can host. Raises
    ``ReshardError``/``ValueError`` loudly on an unhostable or absent
    save — a serving fleet must never boot on silently-wrong weights."""
    import os

    if os.path.isdir(path) and not os.path.exists(
        os.path.join(path, "manifest.json")
    ):
        from .retention import resolve_latest

        resolved = resolve_latest(path)
        if resolved is None:
            raise ReshardError(
                f"checkpoint folder {path!r} holds no complete save"
            )
        return load_serving_params(resolved, init_params, log=log)

    if os.path.isdir(path):
        import jax

        from ..trainer.sharded_ckpt import ShardedCheckpoint, param_key

        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        restored = 0
        out = dict(init_params)
        with ShardedCheckpoint(path) as ck:
            rs = Resharder(ck, log=log)
            saved = set(ck.keys())
            for name, live in init_params.items():
                key = param_key(name)
                if key not in saved:
                    continue
                shape = tuple(ck.manifest["arrays"][key]["shape"])
                want = tuple(np.asarray(live).shape)
                if shape != want:
                    raise ReshardError(
                        f"checkpoint {path!r}: param {name!r} shape "
                        f"{shape} != model shape {want}"
                    )
                out[name] = rs.place(key, sharding)
                restored += 1
            info = {
                "path": path,
                "step": int(ck.step),
                "format": "sharded",
                "saved_nprocs": rs.saved_nprocs,
                "restored": restored,
                "resharded": len(rs.resharded_keys),
            }
        if log is not None and rs.summary():
            log(f"serving restore: {rs.summary()}")
        return out, info

    from ..trainer.checkpoint import load_checkpoint

    step, ck_params, _, _ = load_checkpoint(path)
    out = dict(init_params)
    restored = 0
    for name, arr in ck_params.items():
        if name not in out:
            continue
        if tuple(arr.shape) != tuple(np.asarray(out[name]).shape):
            raise ReshardError(
                f"checkpoint {path!r}: param {name!r} shape "
                f"{tuple(arr.shape)} != model shape "
                f"{tuple(np.asarray(out[name]).shape)}"
            )
        out[name] = arr
        restored += 1
    return out, {
        "path": path,
        "step": int(step),
        "format": "npz",
        "saved_nprocs": 1,
        "restored": restored,
        "resharded": 0,
    }
