"""Zero-stall checkpointing: a two-stage snapshot -> write pipeline.

The synchronous save path (trainer.save -> np.savez) drains the device,
funnels every param through host memory, and blocks the step loop until
the file lands on disk — with ``checkpoint_frequency: 1`` the write IS
the step time. This module takes checkpoint I/O off the step path the
way TensorFlow treats it as background dataflow decoupled from the
training step (PAPERS.md) and Parameter Box keeps parameter movement
off the critical path:

  stage 1 — snapshot (main thread, at the step boundary): the trainer
      runs ONE jitted identity-copy program over params/state/buffers
      (no donation — the live arrays stay valid for the next, donating,
      train step), kicks ``copy_to_host_async()`` on every leaf so the
      device->host DMA overlaps the next steps, and submits the copies
      here. The step loop never waits on disk.

  stage 2 — write (the one writer thread): materialize the host
      snapshot (``np.asarray`` joins the already-running async copies),
      serialize through the existing torn-write discipline (tmp file +
      atomic rename from trainer/checkpoint.py, CRC validation + atomic
      ``LATEST`` from resilience/retention.py via the context's
      ``checkpoint_written`` seam), then pick up the next snapshot.

Memory discipline: snapshots are DOUBLE-buffered. The queue holds at
most one pending snapshot while one write is in flight; a third
``submit`` blocks until the writer frees a slot — backpressure, never
unbounded growth. A job whose write cadence outruns its disk degrades
to the old synchronous stall instead of OOMing.

Ordering and crash safety:

  - one FIFO queue + one writer thread => checkpoints PUBLISH (reach
    ``LATEST``) in step order, always.
  - the writer marks ``LATEST`` only after the file validates (the
    ``checkpoint_written`` callback), so a crash mid-write — proven by
    the ``async_torn_write@K`` injected fault, which tears the K-th
    async write and kills its publication step — leaves ``LATEST`` on
    the previous complete save. Resume falls back exactly as for a
    synchronous torn save.
  - ``flush()`` blocks until everything submitted is on disk: the
    preemption drain calls it before exiting 75 (the final checkpoint
    must be durable before the launcher relaunches), the supervisor
    calls it before resolving ``LATEST`` for a restart, and the guard
    calls it before a rollback restore.

A write failure (disk full, permission) is logged loudly, remembered,
and re-raised by the next ``flush()``/``submit()`` — the step loop
learns about it at the next checkpoint boundary instead of training on
with silently-unsaved state.
"""

from __future__ import annotations

import contextlib
import queue
import threading

from .faults import FaultPlan, tear_file


class AsyncWriteError(RuntimeError):
    """A background checkpoint write failed; raised at the next
    submit/flush so the step loop cannot silently outrun a dead disk."""


#: queue slots for snapshots awaiting the writer: 1 pending + 1 in
#: flight = the double buffer. submit() blocks when both are taken.
_PENDING_SLOTS = 1


class AsyncCheckpointer:
    """The stage-2 writer: one thread, FIFO, double-buffered."""

    def __init__(self, plan: FaultPlan | None = None, log=print):
        self.plan = plan if plan is not None else FaultPlan()
        self.log = log
        #: flight recorder (obs/recorder.py): each background write
        #: becomes a span on its own 'ckpt_writer' track, so a merged
        #: trace shows the write pipeline overlapping the step stream.
        #: None = telemetry off. The recorder is thread-safe.
        self.recorder = None
        self._q: queue.Queue = queue.Queue(maxsize=_PENDING_SLOTS)
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._error: BaseException | None = None
        #: 1-based count of async writes reaching the writer
        #: (``async_torn_write@K`` keys on it, like corrupt_ckpt's save
        #: ordinal — and like it, shared across restart attempts)
        self.write_ordinal = 0
        self.submitted = 0
        #: writes fully published (file on disk + checkpoint_written ran)
        self.published = 0
        #: torn or failed writes (consumed from the queue, unpublished)
        self._consumed_abnormal = 0
        #: high-water mark of snapshots alive at once (tests pin the
        #: double-buffer bound with it)
        self.max_in_flight = 0

    # ------------------------------------------------------------------
    # main-thread API
    # ------------------------------------------------------------------

    def submit(self, step: int, path: str, write_fn, on_written=None) -> None:
        """Queue one snapshot for background serialization.

        ``write_fn()`` must serialize the snapshot to ``path`` with the
        tmp+rename discipline; ``on_written(path, step)`` runs after a
        successful write (validation/LATEST/retention — the context's
        ``checkpoint_written`` seam). Blocks while the double buffer is
        full (backpressure). Raises AsyncWriteError if a previous write
        failed."""
        self._raise_pending()
        self._ensure_thread()
        self._q.put((step, path, write_fn, on_written))
        self.submitted += 1
        self.max_in_flight = max(self.max_in_flight, self.in_flight())

    def in_flight(self) -> int:
        """Snapshots submitted but not yet written (or torn/failed)."""
        return self.submitted - self.published - self._consumed_abnormal

    def flush(self, raise_errors: bool = True) -> None:
        """Block until every submitted snapshot is fully written and
        published. The SIGTERM drain's durability barrier.

        ``raise_errors=False`` (the restart/teardown paths) CONSUMES any
        pending write error instead of re-raising it: the writer already
        logged it loudly, and a stale failure from a crashed attempt
        must not resurface as a spurious "death" of a later, healthy
        attempt."""
        if self._thread is not None:
            self._q.join()
        if raise_errors:
            self._raise_pending()
        else:
            with self._lock:
                self._error = None

    def stop(self) -> None:
        """Flush (swallowing errors — stop runs in ``finally`` paths)
        and shut the writer thread down."""
        t = self._thread
        if t is None:
            return
        self.flush(raise_errors=False)
        self._q.put(None)
        t.join()
        self._thread = None

    # ------------------------------------------------------------------
    # writer thread
    # ------------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="async-ckpt-writer", daemon=True
            )
            self._thread.start()

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise AsyncWriteError(
                f"background checkpoint write failed: "
                f"{type(err).__name__}: {err}"
            ) from err

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            step, path, write_fn, on_written = item
            rec = self.recorder
            span = (
                rec.span("write_checkpoint", track="ckpt_writer")
                if rec is not None
                else contextlib.nullcontext()
            )
            try:
                with span:
                    write_fn()
                self.write_ordinal += 1
                spec = self.plan.fire("async_torn_write", self.write_ordinal)
                if spec is not None:
                    # simulate the writer dying mid-publish: the file is
                    # torn and checkpoint_written (validation + LATEST)
                    # never runs — LATEST must keep naming the previous
                    # complete save
                    tear_file(path)
                    self._consumed_abnormal += 1
                    self.log(
                        f"FAULT: async_torn_write@{self.write_ordinal} — "
                        f"writer died mid-publish of {path} (torn file "
                        "left behind, LATEST untouched)"
                    )
                else:
                    if on_written is not None:
                        on_written(path, step)
                    self.published += 1
            except BaseException as e:  # surface on the main thread
                with self._lock:
                    self._error = e
                self._consumed_abnormal += 1
                self.log(
                    f"ERROR: async checkpoint write of {path} failed — "
                    f"{type(e).__name__}: {e}"
                )
            finally:
                self._q.task_done()
