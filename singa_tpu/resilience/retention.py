"""Checkpoint retention: keep-last-N, a LATEST marker, torn-save defense.

The trainer writes ``step_<N>.npz`` files (or ``step_<N>.ckpt`` sharded
dirs) into ``<workspace>/checkpoints``; this module decides which of
them to trust and which to keep:

  - ``mark_latest`` records the newest *validated* checkpoint in a
    ``LATEST`` marker file, written atomically (tmp + rename) so the
    marker itself can never be torn. The caller validates BEFORE
    marking, so LATEST never points at a torn or corrupt save.
  - ``resolve_latest`` is the restore-side mirror: follow LATEST when
    its target validates, else fall back to scanning every ``step_*``
    entry newest-first and return the first complete one. A job whose
    final save was cut mid-write resumes from the save before it
    instead of crashing on garbage.
  - ``validate_checkpoint`` is the completeness check both sides use:
    npz files must be intact zip archives holding the step key; sharded
    dirs must hold a parseable manifest plus every ``proc_k`` shard the
    manifest promises (CRC-checked) — a torn multi-process save or a
    stale dir from a differently-sized job fails here, loudly. Sharded
    dirs written under the two-phase commit protocol (manifest field
    ``commit``, resilience/coord.py) additionally need every per-proc
    ``commit_k.json`` marker to match its shard's bytes, so a
    half-committed save is never resumable.
  - ``apply_retention`` garbage-collects all but the newest N complete
    checkpoints (never the one LATEST names).
  - ``gc_stale_shards`` removes ``proc_k.npz`` files a previously larger
    job left behind in a sharded dir (k >= the manifest's nprocs) —
    save_sharded now prevents new ones, this cleans up old dirs.

No imports from the trainer package: the supervisor calls this before a
trainer exists, and the trainer's save hook calls it after each write.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import zipfile

from . import coord

LATEST_MARKER = "LATEST"

_STEP_RE = re.compile(r"^step_(\d+)\.(npz|ckpt)$")
_PROC_RE = re.compile(r"^proc_(\d+)\.npz$")
_COMMIT_RE = re.compile(r"^commit_(\d+)\.json$")


def checkpoint_step(path: str) -> int | None:
    """The step number encoded in a checkpoint basename, or None."""
    m = _STEP_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def _npz_valid(path: str) -> bool:
    """Intact zip archive holding the ``__step__`` entry. ``testzip``
    CRC-checks every member, so a truncated or bit-flipped save fails
    even though np.load's lazy zip layer might open it. (The replica
    ``.server`` sidecar is only commit-verified for SHARDED saves —
    ``_sharded_valid`` + the manifest's ``sidecar`` promise; the npz
    format has no commit machinery to ride.)"""
    try:
        with zipfile.ZipFile(path) as z:
            if z.testzip() is not None:
                return False
            return any(n.startswith("__step__") for n in z.namelist())
    except (OSError, zipfile.BadZipFile, ValueError):
        return False


def _sharded_valid(path: str) -> bool:
    """Manifest parses, every promised proc shard is an intact zip, and
    — for saves written under the two-phase commit protocol — every
    per-proc commit marker matches its shard's bytes. A save missing
    even one peer's commit (rank died between shard and marker, or the
    marker itself was torn) is NOT a checkpoint. A manifest that
    promises a replica ``.server`` sidecar (``"sidecar": true``,
    trainer/replica.py) additionally needs the sidecar file AND its
    ``commit_server.json`` marker to match — a rank that died between
    shard commit and sidecar, or tore the sidecar afterwards, must not
    leave a resumable-looking save whose protocol state is garbage."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    if manifest.get("format") != "singa-tpu-sharded-v1":
        return False
    if manifest.get("sidecar") and not coord.sidecar_commit_ok(path):
        return False
    nprocs = int(manifest.get("nprocs", 1))
    committed = manifest.get("commit") == coord.COMMIT_VERSION
    for k in range(nprocs):
        if committed:
            # the marker's whole-file size+CRC32 subsumes the zip
            # member walk for tear detection — one read per shard on
            # process 0's promotion path, not two
            if not coord.commit_ok(path, k):
                return False
            continue
        shard = os.path.join(path, f"proc_{k}.npz")
        try:
            with zipfile.ZipFile(shard) as z:
                if z.testzip() is not None:
                    return False
        except (OSError, zipfile.BadZipFile, ValueError):
            return False
    return True


#: validation cache: path -> fingerprint of the last content this module
#: fully CRC-validated. The per-save retention pass re-walks EVERY kept
#: checkpoint through validate_checkpoint; without the cache that walk
#: (zipfile.testzip CRC over every member of every archive) grows with
#: keep_last and bounds the async writer's throughput. A fingerprint is
#: (mtime_ns, size) per constituent file, so any rewrite/tear/truncation
#: forces a real re-validation.
_VALIDATED: dict[str, tuple] = {}
_VALIDATED_CAP = 256


def validation_cache_clear() -> None:
    """Drop every cached validation verdict (tests; paranoia)."""
    _VALIDATED.clear()


def _fingerprint(path: str) -> tuple | None:
    """Stat-level identity of a checkpoint's bytes, or None when it
    cannot be stat'ed (never cache what cannot be re-checked)."""
    try:
        if os.path.isdir(path):
            names = ["manifest.json"] + sorted(
                f
                for f in os.listdir(path)
                if _PROC_RE.match(f)
                or _COMMIT_RE.match(f)
                or f == "commit_server.json"
            )
            fp = []
            for name in names:
                st = os.stat(os.path.join(path, name))
                fp.append((name, st.st_mtime_ns, st.st_size))
            # the replica .server sidecar lives BESIDE the dir; a tear
            # of it must invalidate the cached verdict too
            try:
                st = os.stat(path + ".server")
                fp.append((".server", st.st_mtime_ns, st.st_size))
            except OSError:
                pass
            return tuple(fp)
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)
    except OSError:
        return None


def _forget_validated(path: str) -> None:
    _VALIDATED.pop(path, None)


def validate_checkpoint(path: str) -> bool:
    """True iff ``path`` is a complete, readable checkpoint.

    Positive verdicts are cached by content fingerprint: a checkpoint
    this process already CRC-validated is only re-walked when its files'
    (mtime, size) changed. Negative verdicts are never cached — a save
    that looks torn may simply still be in flight."""
    fp = _fingerprint(path)
    if fp is not None and _VALIDATED.get(path) == fp:
        return True
    if os.path.isdir(path):
        ok = _sharded_valid(path)
    else:
        ok = os.path.isfile(path) and _npz_valid(path)
    if ok and fp is not None:
        # the fingerprint was taken BEFORE the walk: if a concurrent
        # writer changed the file mid-validation, the stale fingerprint
        # mismatches next time and forces a re-check — the safe side
        if len(_VALIDATED) >= _VALIDATED_CAP:
            for stale in [p for p in _VALIDATED if not os.path.exists(p)]:
                _VALIDATED.pop(stale, None)
            if len(_VALIDATED) >= _VALIDATED_CAP:
                _VALIDATED.clear()  # pathological churn; correctness first
        _VALIDATED[path] = fp
    return ok


def list_checkpoints(folder: str) -> list[str]:
    """``step_*`` entries under ``folder``, newest step first (no
    validation — callers validate the ones they intend to trust)."""
    try:
        names = os.listdir(folder)
    except OSError:
        return []
    found = []
    for name in names:
        m = _STEP_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(folder, name)))
    return [p for _, p in sorted(found, reverse=True)]


def mark_latest(folder: str, path: str) -> None:
    """Atomically point ``folder/LATEST`` at ``path`` (a checkpoint in
    ``folder``). Callers must have validated ``path`` first — the marker
    is the trust anchor a restarted job follows blindly."""
    marker = os.path.join(folder, LATEST_MARKER)
    tmp = marker + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(os.path.basename(path) + "\n")
    os.replace(tmp, marker)


def resolve_latest(folder: str | None) -> str | None:
    """The newest complete checkpoint under ``folder``: the LATEST
    target when it validates, else the newest ``step_*`` entry that
    does; None when nothing trustworthy exists."""
    if not folder or not os.path.isdir(folder):
        return None
    marker = os.path.join(folder, LATEST_MARKER)
    try:
        with open(marker, "r", encoding="utf-8") as f:
            name = f.read().strip()
    except OSError:
        name = ""
    if name:
        target = os.path.join(folder, os.path.basename(name))
        if validate_checkpoint(target):
            return target
    for path in list_checkpoints(folder):
        if validate_checkpoint(path):
            return path
    return None


def apply_retention(folder: str, keep_last: int, *,
                    current_nprocs: int | None = None) -> list[str]:
    """Delete all but the newest ``keep_last`` complete checkpoints
    (invalid ones are deleted regardless — they can never be restored
    — except the newest entry, which may still be mid-write by a
    concurrent saver). The LATEST target always survives. Returns the
    deleted paths. ``keep_last <= 0`` keeps everything.

    With ``current_nprocs`` given (the live job's process count), the
    keep budget PREFERS saves written by the current topology: a
    sharded save from a since-resized job restores only through the
    reshard path, so when trimming, stale-topology saves evict first —
    newest current-topology saves fill the budget, then the newest
    stale ones take whatever budget remains. npz saves are
    topology-agnostic (host-assembled, restorable anywhere) and always
    count as current. ``None`` keeps the pure newest-first order."""
    if keep_last <= 0:
        return []
    marker = os.path.join(folder, LATEST_MARKER)
    pinned = ""
    try:
        with open(marker, "r", encoding="utf-8") as f:
            pinned = f.read().strip()
    except OSError:
        pass
    entries = [
        (path, validate_checkpoint(path))
        for path in list_checkpoints(folder)
    ]
    keep_set: set[str] | None = None
    if current_nprocs is not None:
        from .reshard import checkpoint_nprocs

        def stale(path: str) -> bool:
            nprocs = checkpoint_nprocs(path)
            return nprocs is not None and nprocs != current_nprocs

        ranked = [p for p, valid in entries if valid and not stale(p)]
        ranked += [p for p, valid in entries if valid and stale(p)]
        keep_set = set(ranked[:keep_last])
    deleted: list[str] = []
    kept = 0
    for i, (path, valid) in enumerate(entries):
        if keep_set is None:
            keep = valid and kept < keep_last
        else:
            keep = path in keep_set
        keep = keep or bool(
            pinned and os.path.basename(path) == pinned
        )
        if not valid and i == 0:
            keep = True  # newest entry may be a concurrent in-flight save
        if keep:
            kept += int(valid)
            continue
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
            deleted.append(path)
            _forget_validated(path)
        except OSError:
            pass
        # the replica engine writes a `<ckpt>.server` sidecar (center +
        # protocol snapshot, trainer/replica.py) the size of the whole
        # server tree — it must not outlive its checkpoint
        sidecar = path + ".server"
        if os.path.isfile(sidecar):
            try:
                os.unlink(sidecar)
                deleted.append(sidecar)
            except OSError:
                pass
    return deleted


def remove_stale_shards(path: str, nprocs: int) -> list[str]:
    """Remove ``proc_k.npz`` / ``commit_k.json`` (and torn ``.tmp``)
    files in a sharded checkpoint dir for k >= ``nprocs`` — leftovers
    from a previously larger job that the loader would silently never
    read (and whose stale commit markers would vouch for shards that no
    longer belong to the save). The ONE copy of this delete loop:
    ``save_sharded`` calls it with the live process count before
    writing its manifest, ``gc_stale_shards`` with the manifest's own
    count for already-written dirs. Files for k < nprocs are never
    touched (a peer process may be mid-write)."""
    removed = []
    try:
        names = os.listdir(path)
    except OSError:
        return removed
    for fname in names:
        base = fname[:-4] if fname.endswith(".tmp") else fname
        m = _PROC_RE.match(base) or _COMMIT_RE.match(base)
        if m and int(m.group(1)) >= nprocs:
            full = os.path.join(path, fname)
            try:
                os.unlink(full)
                removed.append(full)
            except OSError:
                pass
    return removed


def gc_stale_shards(path: str) -> list[str]:
    """``remove_stale_shards`` driven by the manifest's own nprocs —
    cleans dirs written before save_sharded grew its at-save GC.
    Returns the removed paths; no-op for npz checkpoints."""
    if not os.path.isdir(path):
        return []
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            nprocs = int(json.load(f).get("nprocs", 1))
    except (OSError, ValueError):
        return []
    return remove_stale_shards(path, nprocs)
