"""Divergence guard: on-device bad-step detection, host-side policy.

The detection half lives INSIDE the jitted train step (trainer.py
``_train_step_fn``): one fused finiteness verdict over the step's loss
and global grad-norm, folded into the step's own outputs — the guard
counters ride the buffer pytree the step already threads, so a guarded
run does exactly as many host syncs as an unguarded one (none per step;
self-lint's JAX-hazard pass stays clean).

Policies (ResilienceConfig.guard_policy):

  kSkip      a bad step's param/state/buffer updates are dropped on
             device (``where(ok, new, old)``) and the bad-step counters
             increment; training continues on the pre-step state.
  kRollback  kSkip, plus: when ``guard_rollback_after`` consecutive
             steps are bad, the host (checking the counter only at
             step-boundary cadence, resilience/context.py) restores the
             last complete checkpoint and backs the effective LR off by
             ``guard_lr_backoff`` — the accumulated scale multiplies the
             gradients inside the step, so the backoff also needs no
             recompile and no host sync.

The counters live in the buffers dict under reserved dunder keys, so
they checkpoint/restore with the rest of training state for free.
Supported on the backprop engine (the base Trainer step); the CD and
replica engines override the step body and reject guard configs loudly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

class GuardGaveUp(RuntimeError):
    """kRollback rolled back repeatedly without getting past the step
    that tripped it — the divergence is deterministic (e.g. NaN baked
    into the data stream), so replaying the same checkpoint + stream
    positions can never succeed. Raised instead of livelooping; the
    supervisor treats it like any crash and its circuit breaker gives
    up loudly."""


#: reserved buffer keys (never collide with layer buffers, which are
#: namespaced by layer name)
GUARD_CONSEC = "__guard_consec__"  # consecutive bad steps (int32)
GUARD_BAD = "__guard_bad__"  # total bad steps this run (int32)
GUARD_LR = "__guard_lr_scale__"  # accumulated LR backoff (float32)
GUARD_KEYS = (GUARD_CONSEC, GUARD_BAD, GUARD_LR)


@dataclasses.dataclass(frozen=True)
class GuardSpec:
    """The trainer-facing slice of ResilienceConfig's guard fields."""

    policy: str  # "kSkip" | "kRollback"
    rollback_after: int
    lr_backoff: float

    @staticmethod
    def from_config(res_cfg) -> "GuardSpec | None":
        """-> GuardSpec, or None when no guard is configured."""
        if res_cfg is None or res_cfg.guard_policy == "kNone":
            return None
        return GuardSpec(
            policy=res_cfg.guard_policy,
            rollback_after=max(1, res_cfg.guard_rollback_after),
            lr_backoff=res_cfg.guard_lr_backoff,
        )


def init_guard_buffers() -> dict[str, jnp.ndarray]:
    """Fresh counters for a guarded run (merged into init buffers, so
    they persist through checkpoints like any other buffer)."""
    return {
        GUARD_CONSEC: jnp.int32(0),
        GUARD_BAD: jnp.int32(0),
        GUARD_LR: jnp.float32(1.0),
    }


def grad_norm_sq(grads) -> jnp.ndarray:
    """Global squared grad-norm, accumulated in fp32 (a single scalar —
    NaN/Inf anywhere in any gradient poisons it, which is the point)."""
    total = jnp.float32(0.0)
    for g in jax.tree.leaves(grads):
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return total


def apply_verdict(ok, new_tree, old_tree):
    """``where(ok, new, old)`` over a pytree — the on-device skip."""
    return jax.tree.map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree
    )


def step_guard_buffers(ok, buffers) -> dict[str, jnp.ndarray]:
    """The post-step guard counters (same dtypes as init, so the chunk
    engine's lax.scan carry stays fixed-shape)."""
    bad = (~ok).astype(jnp.int32)
    return {
        GUARD_CONSEC: jnp.where(
            ok, jnp.int32(0), buffers[GUARD_CONSEC] + 1
        ).astype(jnp.int32),
        GUARD_BAD: (buffers[GUARD_BAD] + bad).astype(jnp.int32),
        GUARD_LR: buffers[GUARD_LR],
    }
