"""Divergence guard: on-device bad-step detection, host-side policy.

The detection half lives INSIDE the jitted train step (each engine's
``_step_core``): one fused finiteness verdict over the step's own
health signals, folded into the step's outputs — the guard counters
ride the buffer pytree the step already threads, so a guarded run does
exactly as many host syncs as an unguarded one (none per step;
self-lint's JAX-hazard pass stays clean).

Policies (ResilienceConfig.guard_policy):

  kSkip      a bad step's param/state/buffer updates are dropped on
             device (``where(ok, new, old)``) and the bad-step counters
             increment; training continues on the pre-step state.
  kRollback  kSkip, plus: when ``guard_rollback_after`` consecutive
             steps are bad, the host (checking the counter only at
             step-boundary cadence, resilience/context.py) restores the
             last complete checkpoint and backs the effective LR off by
             ``guard_lr_backoff`` — the accumulated scale multiplies the
             gradients inside the step, so the backoff also needs no
             recompile and no host sync.

The counters live in the buffers dict under reserved dunder keys, so
they checkpoint/restore with the rest of training state for free.

All three engines share ONE wrapper (``guarded_step``): each engine
implements a ``_step_core`` that computes its update plus its own
finiteness verdict (base: loss + global grad-norm; replica: every
replica's loss + grad-norm — any bad replica voids the whole step, so
the shared counters and a rollback stay consistent across replicas;
CD: the CD grads + per-RBM metrics), scales its gradients by the
accumulated LR backoff, and the wrapper applies the verdict to
params/state/buffers and threads the counters — identically for every
engine, including the replica engine's ``.server`` sidecar state
(rollback restores it through the engine's own resume path).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

class GuardGaveUp(RuntimeError):
    """kRollback rolled back repeatedly without getting past the step
    that tripped it — the divergence is deterministic (e.g. NaN baked
    into the data stream), so replaying the same checkpoint + stream
    positions can never succeed. Raised instead of livelooping; the
    supervisor treats it like any crash and its circuit breaker gives
    up loudly."""


#: reserved buffer keys (never collide with layer buffers, which are
#: namespaced by layer name)
GUARD_CONSEC = "__guard_consec__"  # consecutive bad steps (int32)
GUARD_BAD = "__guard_bad__"  # total bad steps this run (int32)
GUARD_LR = "__guard_lr_scale__"  # accumulated LR backoff (float32)
GUARD_KEYS = (GUARD_CONSEC, GUARD_BAD, GUARD_LR)


@dataclasses.dataclass(frozen=True)
class GuardSpec:
    """The trainer-facing slice of ResilienceConfig's guard fields."""

    policy: str  # "kSkip" | "kRollback"
    rollback_after: int
    lr_backoff: float

    @staticmethod
    def from_config(res_cfg) -> "GuardSpec | None":
        """-> GuardSpec, or None when no guard is configured."""
        if res_cfg is None or res_cfg.guard_policy == "kNone":
            return None
        return GuardSpec(
            policy=res_cfg.guard_policy,
            rollback_after=max(1, res_cfg.guard_rollback_after),
            lr_backoff=res_cfg.guard_lr_backoff,
        )


def init_guard_buffers() -> dict[str, jnp.ndarray]:
    """Fresh counters for a guarded run (merged into init buffers, so
    they persist through checkpoints like any other buffer)."""
    return {
        GUARD_CONSEC: jnp.int32(0),
        GUARD_BAD: jnp.int32(0),
        GUARD_LR: jnp.float32(1.0),
    }


def grad_norm_sq(grads) -> jnp.ndarray:
    """Global squared grad-norm, accumulated in fp32 (a single scalar —
    NaN/Inf anywhere in any gradient poisons it, which is the point)."""
    total = jnp.float32(0.0)
    for g in jax.tree.leaves(grads):
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return total


def apply_verdict(ok, new_tree, old_tree):
    """``where(ok, new, old)`` over a pytree — the on-device skip."""
    return jax.tree.map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree
    )


def step_guard_buffers(ok, buffers) -> dict[str, jnp.ndarray]:
    """The post-step guard counters (same dtypes as init, so the chunk
    engine's lax.scan carry stays fixed-shape)."""
    bad = (~ok).astype(jnp.int32)
    return {
        GUARD_CONSEC: jnp.where(
            ok, jnp.int32(0), buffers[GUARD_CONSEC] + 1
        ).astype(jnp.int32),
        GUARD_BAD: (buffers[GUARD_BAD] + bad).astype(jnp.int32),
        GUARD_LR: buffers[GUARD_LR],
    }


def split_guard_buffers(buffers) -> tuple[dict, dict]:
    """-> (layer buffers, guard counters) — engines' step cores see
    only the layer half; the wrapper owns the counters."""
    layer = {k: v for k, v in buffers.items() if k not in GUARD_KEYS}
    g = {k: buffers[k] for k in GUARD_KEYS if k in buffers}
    return layer, g


def guarded_step(core, params, state, buffers, step, batch, rng):
    """The ONE engine-independent guard wrapper (runs inside the jitted
    step, zero host syncs).

    ``core(params, state, layer_buffers, step, batch, rng, lr_scale)``
    -> ``(new_params, new_state, new_layer_buffers, metrics, ok)``:
    the engine's own update with ``lr_scale`` folded into its grads and
    ``ok`` its scalar finiteness verdict. The wrapper drops a bad
    step's updates on device (``where(ok, new, old)`` over every tree),
    zeroes its metrics (a NaN must not pollute the display window's
    running sums), and threads the counters through the buffer pytree.
    """
    lr_scale = buffers[GUARD_LR]
    layer_bufs, _ = split_guard_buffers(buffers)
    new_p, new_s, new_b, metrics, ok = core(
        params, state, layer_bufs, step, batch, rng, lr_scale
    )
    out_params = apply_verdict(ok, new_p, params)
    out_state = apply_verdict(ok, new_s, state)
    # only keys the core returned (forward may thread a subset); old
    # values come from the pre-step buffers
    out_buffers = dict(
        apply_verdict(ok, new_b, {k: buffers[k] for k in new_b})
    )
    out_buffers.update(step_guard_buffers(ok, buffers))
    metrics = jax.tree.map(
        lambda m: jnp.where(ok, m, jnp.zeros_like(m)), metrics
    )
    return out_params, out_state, out_buffers, metrics
