"""Step-wall-clock watchdog + peer-liveness heartbeats.

A multi-host collective that loses a peer does not crash — it hangs, and
the job burns its reservation in silence. Two defenses live here:

**Stall diagnostics** (single- and multi-host): a host-side daemon
thread fed a heartbeat at every step/chunk boundary; when the gap since
the last beat exceeds the configured timeout it dumps diagnostics (the
stalled step number, the elapsed time, and every thread's Python stack)
through the job log, once per stall. It never kills anything — a
transient straggler must not become a guaranteed restart.

**Peer liveness** (multi-host): each rank's watchdog thread touches a
per-rank heartbeat file (``<workspace>/heartbeats/rank_k.hb``) every
poll — file freshness means "process alive", deliberately NOT "step
advancing", so a peer grinding through a slow compile never reads as
dead. When (a) our OWN step has been stalled longer than the peer
deadline — we are stuck, almost certainly in a collective — and (b) a
peer's heartbeat file is stale past the same deadline, the peer process
is presumed dead and this rank exits with the RESUMABLE status (75): a
forever-hung collective becomes a loud, launcher-restartable event. A
rank that exits deliberately (trained to completion, or a coordinated
preemption drain) publishes a ``rank_k.done`` sentinel first, so its
now-frozen heartbeat is never mistaken for a death.

Staleness is judged by TWO signals, either of which proves life: the
file's mtime, and a monotonic beat counter written into the file body.
The counter exists for workspaces on object-store/NFS mounts whose
mtimes are coarse (second granularity), cached, or clock-skewed across
hosts — there a perfectly healthy peer's mtime can read stale for
longer than a tight deadline, and mtime alone would false-positive
``peer_death`` and kill a live job. A counter that ADVANCES between our
polls restarts the peer's staleness clock locally (observer-side
monotonic time, no cross-host clock comparison at all); a frozen
counter leaves the verdict to the mtime-vs-arming math exactly as
before, so bodiless heartbeat files from older runs still work.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from .preemption import EXIT_RESUMABLE


def heartbeat_file(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{rank}.hb")


def read_heartbeat_counter(path: str) -> int | None:
    """The monotonic beat counter in a heartbeat file's body, or None
    (absent file, empty/foreign body — e.g. a pre-counter run's
    touch-only file). Never raises: liveness must degrade to the mtime
    signal, not crash the watch thread."""
    try:
        with open(path, "rb") as f:
            return int(f.read(32).split(b"\n", 1)[0])
    except (OSError, ValueError):
        return None


def done_file(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{rank}.done")


class Watchdog:
    """Monitor thread: ``beat(step)`` at boundaries, dump on stall,
    optionally watch peer heartbeats (``enable_heartbeats``)."""

    def __init__(self, timeout: float, log=print):
        self.timeout = float(timeout)
        self.log = log
        #: flight recorder (obs/recorder.py): stall dumps and peer-death
        #: verdicts were stderr-only — as events they survive into the
        #: post-mortem trace even when nobody captured the process's
        #: stderr. None = telemetry off.
        self.recorder = None
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._last_step = -1
        self._dumped_for = -2  # step already diagnosed (once per stall)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: stall dumps emitted (tests and post-mortems read this)
        self.stalls = 0
        #: peer-liveness state (None = disabled); see enable_heartbeats
        self._hb: dict | None = None
        #: orders mark_done's final touch+sentinel against the watch
        #: thread's periodic touches (sentinel mtime must stay >= our
        #: heartbeat mtime once we declare the exit deliberate)
        self._hb_lock = threading.Lock()
        #: peers this instance declared dead (tests read it; also keeps
        #: a non-exiting on_peer_dead callback from firing per poll)
        self.dead_peers: set[int] = set()
        #: our own monotonic beat counter (heartbeat file body)
        self._beat_seq = 0
        #: peer rank -> (last counter seen, staleness clock, last time
        #: WE looked): the observer-side staleness clock that makes
        #: peer liveness survive coarse-mtime filesystems. The
        #: last-look stamp distinguishes "counter advanced since a
        #: poll moments ago" (alive) from "counter differs from an
        #: observation made during a stall episode hours back" (no
        #: evidence either way — start fresh)
        self._peer_seen: dict[int, tuple[int, float, float]] = {}

    def enable_heartbeats(
        self,
        directory: str,
        rank: int,
        nprocs: int,
        peer_timeout: float,
        on_peer_dead=None,
    ) -> None:
        """Arm peer liveness BEFORE ``start()``: touch our own heartbeat
        file every poll, and declare a peer dead when its file is stale
        past ``peer_timeout`` seconds while our own step is stalled at
        least as long. ``on_peer_dead(rank, age)`` defaults to a loud
        resumable exit (os._exit(75)). Peers get a full ``peer_timeout``
        of grace from the moment we arm — a rank still initializing is
        not dead."""
        os.makedirs(directory, exist_ok=True)
        self._hb = {
            "dir": directory,
            "rank": int(rank),
            "nprocs": int(nprocs),
            "timeout": float(peer_timeout),
            # wall clock, because it is compared against file mtimes
            "enabled_at": time.time(),
            "on_dead": on_peer_dead or self._exit_peer_dead,
            "done": False,
        }
        # a fresh incarnation of this rank: a stale done sentinel from
        # the previous run must not mask THIS run's death to our peers
        try:
            os.unlink(done_file(directory, int(rank)))
        except OSError:
            pass
        self._touch_heartbeat()

    def mark_done(self) -> None:
        """Publish "this rank exited deliberately" (end of training, or
        a coordinated drain): peers must not read the now-frozen
        heartbeat as a death. The final heartbeat touch and the
        sentinel write happen under the same lock the watch thread's
        periodic touch takes, so sentinel mtime >= heartbeat mtime
        holds — a racing touch can never reorder past it."""
        hb = self._hb
        if hb is None:
            return
        with self._hb_lock:
            hb["done"] = True  # the watch thread stops touching
            self._touch_heartbeat()
            with open(done_file(hb["dir"], hb["rank"]), "w"):
                pass

    def start(self) -> None:
        if (self.timeout <= 0 and self._hb is None) or self._thread:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="singa-tpu-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def beat(self, step: int) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._last_step = step

    # ------------------------------------------------------------------
    # watch thread
    # ------------------------------------------------------------------

    def _poll_interval(self) -> float:
        ts = [self.timeout]
        if self._hb is not None:
            ts.append(self._hb["timeout"])
        ts = [t for t in ts if t > 0]
        t = min(ts) if ts else 1.0
        # poll fast enough to catch a stall promptly without busy-waiting
        return max(0.01, min(t / 4.0, 1.0))

    def _watch(self) -> None:
        poll = self._poll_interval()
        while not self._stop.wait(poll):
            hb = self._hb
            if hb is not None:
                with self._hb_lock:
                    if not hb["done"]:
                        self._touch_heartbeat()
            with self._lock:
                elapsed = time.monotonic() - self._last_beat
                step, dumped = self._last_step, self._dumped_for
            if hb is not None and elapsed > hb["timeout"]:
                self._check_peers(hb)
            if self.timeout <= 0 or elapsed <= self.timeout or step == dumped:
                continue
            self._dump(step, elapsed)
            with self._lock:
                self._dumped_for = step
                self.stalls += 1

    def _touch_heartbeat(self) -> None:
        hb = self._hb
        path = heartbeat_file(hb["dir"], hb["rank"])
        self._beat_seq += 1
        try:
            # mtime AND a monotonic counter in the body: coarse-mtime
            # mounts (object store / NFS) get their liveness from the
            # advancing counter. Published atomically (tmp + rename,
            # the coord-plane primitive): a truncate-then-write here
            # would hand a racing reader an EMPTY body — and on exactly
            # the coarse-mtime mounts the counter exists for, "fall
            # back to mtime" IS the false-positive death verdict.
            from .coord import atomic_write_bytes

            atomic_write_bytes(
                path, f"{self._beat_seq}\n".encode("ascii")
            )
            os.utime(path, None)
        except OSError:
            pass  # a flaky shared FS must not kill the watchdog thread

    @staticmethod
    def _mtime(path: str) -> float | None:
        try:
            return os.stat(path).st_mtime
        except OSError:
            return None

    def _check_peers(self, hb: dict) -> None:
        """Our own step is stalled past the peer deadline — are we stuck
        because a peer process died mid-collective?"""
        now = time.time()
        now_mono = time.monotonic()
        for k in range(hb["nprocs"]):
            if k == hb["rank"] or k in self.dead_peers:
                continue
            peer_path = heartbeat_file(hb["dir"], k)
            hb_m = self._mtime(peer_path)
            # grace from arming: a peer that has not beaten yet is
            # (still) initializing, not dead
            age = now - max(hb_m or 0.0, hb["enabled_at"])
            if age <= hb["timeout"]:
                continue
            # second signal: the body's beat counter. An mtime stale
            # past the deadline on a coarse-mtime mount says nothing if
            # the counter is still advancing — restart the staleness
            # clock from OUR OWN monotonic observation of the change
            # (no cross-host clock enters the verdict). The FIRST
            # observation is backdated to two polls short of the
            # deadline: a live peer gets two polls to demonstrate an
            # advancing counter, while a genuinely dead peer's verdict
            # lands ~two polls after the mtime deadline — not a whole
            # extra timeout of silent hang.
            seq = read_heartbeat_counter(peer_path)
            if seq is not None:
                last = self._peer_seen.get(k)
                if last is None or now_mono - last[2] > hb["timeout"]:
                    # first look — or our last look predates this
                    # stall episode (_check_peers only runs while WE
                    # are stalled), so a differing counter would say
                    # nothing about the peer's recent liveness. Start
                    # a fresh clock, backdated to two polls short of
                    # the deadline: the peer beats at the same
                    # ~timeout/4 cadence we poll at, so a live one
                    # gets two observation gaps to demonstrate an
                    # advancing counter while a dead one's verdict
                    # lands ~two polls later — not a whole extra
                    # timeout of silent hang
                    grace = 2.0 * self._poll_interval()
                    self._peer_seen[k] = (
                        seq, now_mono - hb["timeout"] + grace, now_mono
                    )
                    continue
                if last[0] != seq:
                    self._peer_seen[k] = (seq, now_mono, now_mono)
                    continue  # advanced since our last look: alive
                self._peer_seen[k] = (seq, last[1], now_mono)
                if now_mono - last[1] <= hb["timeout"]:
                    continue  # changed recently enough: alive
            done_m = self._mtime(done_file(hb["dir"], k))
            deliberate = (
                done_m is not None
                and (hb_m is None or done_m >= hb_m)
                # a sentinel older than OUR arming (minus one deadline
                # of clock slack) is a PREVIOUS incarnation's clean
                # exit — a peer that died in THIS run before arming
                # (it clears its own sentinel at enable_heartbeats)
                # must not be masked by it
                and done_m >= hb["enabled_at"] - hb["timeout"]
            )
            if deliberate:
                continue  # deliberate exit (trained / coordinated drain)
            self.dead_peers.add(k)
            if self.recorder is not None:
                # recorded (and flushed) BEFORE the verdict callback:
                # the default callback is os._exit(75), which would
                # otherwise take the buffered verdict down with it
                self.recorder.event(
                    "peer_death", peer=k, stale_s=round(age, 3),
                    deadline_s=hb["timeout"],
                )
                self.recorder.flush()
            hb["on_dead"](k, age)

    def _exit_peer_dead(self, rank: int, age: float) -> None:
        hb = self._hb
        self.log(
            f"WATCHDOG: peer rank {rank} heartbeat stale {age:.1f}s "
            f"(deadline {hb['timeout']:.1f}s) while this rank's step is "
            "stalled — peer presumed dead mid-collective; exiting "
            f"resumable ({EXIT_RESUMABLE}) so the launcher can restart "
            "every rank from the last complete checkpoint"
        )
        sys.stdout.flush()
        sys.stderr.flush()
        # the hung collective can never complete once the peer is gone;
        # os._exit is the only exit that does not need the main thread
        os._exit(EXIT_RESUMABLE)

    def _dump(self, step: int, elapsed: float) -> None:
        lines = [
            f"WATCHDOG: step {step} has run {elapsed:.1f}s "
            f"(timeout {self.timeout:.1f}s) — possible hung collective "
            "or straggler; thread stacks follow"
        ]
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if names.get(ident) == "singa-tpu-watchdog":
                continue
            lines.append(f"--- thread {names.get(ident, ident)} ---")
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        self.log("\n".join(lines))
        if self.recorder is not None:
            # the full dump rides the event (bounded — a pathological
            # thread count must not bloat the log past usefulness);
            # flushed now because a stalled run may never reach its
            # next display boundary
            self.recorder.event(
                "watchdog_stall", step=step,
                elapsed_s=round(elapsed, 3), timeout_s=self.timeout,
                stacks="\n".join(lines[1:])[:16384],
            )
            self.recorder.flush()
