"""Step-wall-clock watchdog: hung-collective detection.

A multi-host collective that loses a peer does not crash — it hangs, and
the job burns its reservation in silence. The watchdog is a host-side
daemon thread fed a heartbeat at every step/chunk boundary; when the gap
since the last beat exceeds the configured timeout it dumps diagnostics
(the stalled step number, the elapsed time, and every thread's Python
stack) through the job log, once per stall. It never kills anything —
the operator (or an external supervisor watching the log) decides;
killing from a watchdog thread would turn a transient straggler into a
guaranteed restart.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback


class Watchdog:
    """Monitor thread: ``beat(step)`` at boundaries, dump on stall."""

    def __init__(self, timeout: float, log=print):
        self.timeout = float(timeout)
        self.log = log
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._last_step = -1
        self._dumped_for = -2  # step already diagnosed (once per stall)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: stall dumps emitted (tests and post-mortems read this)
        self.stalls = 0

    def start(self) -> None:
        if self.timeout <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="singa-tpu-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def beat(self, step: int) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._last_step = step

    def _watch(self) -> None:
        # poll fast enough to catch a stall promptly without busy-waiting
        poll = max(0.01, min(self.timeout / 4.0, 1.0))
        while not self._stop.wait(poll):
            with self._lock:
                elapsed = time.monotonic() - self._last_beat
                step, dumped = self._last_step, self._dumped_for
            if elapsed <= self.timeout or step == dumped:
                continue
            self._dump(step, elapsed)
            with self._lock:
                self._dumped_for = step
                self.stalls += 1

    def _dump(self, step: int, elapsed: float) -> None:
        lines = [
            f"WATCHDOG: step {step} has run {elapsed:.1f}s "
            f"(timeout {self.timeout:.1f}s) — possible hung collective "
            "or straggler; thread stacks follow"
        ]
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if names.get(ident) == "singa-tpu-watchdog":
                continue
            lines.append(f"--- thread {names.get(ident, ident)} ---")
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        self.log("\n".join(lines))
