"""Launcher-side restart budget + the elastic gang-relaunch loop.

The in-process supervisor (supervisor.py) already breaks crash loops
WITHIN one process lifetime — but its circuit breaker deliberately does
not count resumable exits (75): a preemption drain or a peer-death
watchdog exit is supposed to be relaunched. That leaves a hole at the
LAUNCHER: a deterministic drain/death cycle (a host that always gets
preempted at step K, a node whose peer always dies) makes every gang
attempt exit 75, and a launcher that blindly relaunches resumable
statuses loops forever, burning the reservation.

``RestartBudget`` closes it: at most ``max_restarts_per_window``
relaunches per rolling ``restart_window_s`` seconds (the
``resilience {}`` conf knobs), after which the launcher gives up
loudly. It is deliberately DISTINCT from the in-process breaker —
the breaker keys on training progress, the budget keys on wall clock,
because a relaunch cycle that makes progress every time can still be
pathological if it churns the fleet every few seconds.

``supervise_gang`` is the relaunch loop itself, factored process-free
(it drives any ``run_gang()`` callable) so the budget policy is
testable without OS processes; ``tools/elastic_launch.py`` wires it to
real ``python -m singa_tpu.main`` ranks — including relaunching at a
DIFFERENT ``-nprocs`` than the drained gang ran with, which the
reshard-on-restore path (resilience/reshard.py) makes a no-op for the
training state.
"""

from __future__ import annotations

import time

from .preemption import EXIT_FAILED, EXIT_OK, EXIT_RESUMABLE


class RestartBudget:
    """At most ``max_per_window`` spends per rolling ``window_s``
    seconds. ``max_per_window <= 0`` = unbudgeted (every spend
    granted). ``clock`` is injectable for tests (monotonic seconds)."""

    def __init__(
        self,
        max_per_window: int,
        window_s: float,
        *,
        clock=time.monotonic,
    ):
        self.max_per_window = int(max_per_window)
        self.window_s = float(window_s)
        self._clock = clock
        self._spent: list[float] = []  # spend timestamps, oldest first

    @classmethod
    def from_config(cls, res_cfg) -> "RestartBudget":
        """Budget from a ``ResilienceConfig`` (None = unbudgeted)."""
        if res_cfg is None:
            return cls(0, 0.0)
        return cls(
            getattr(res_cfg, "max_restarts_per_window", 0),
            getattr(res_cfg, "restart_window_s", 3600.0),
        )

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._spent and self._spent[0] <= cutoff:
            self._spent.pop(0)

    @property
    def used(self) -> int:
        """Spends still inside the rolling window."""
        self._prune(self._clock())
        return len(self._spent)

    def spend(self) -> bool:
        """Take one restart from the budget; False = budget exhausted
        (the caller must give up instead of relaunching)."""
        now = self._clock()
        self._prune(now)
        if self.max_per_window > 0 and len(self._spent) >= self.max_per_window:
            return False
        self._spent.append(now)
        return True


def gang_verdict(exit_codes: list[int]) -> str:
    """Classify one gang attempt's exit codes:

    ``ok``         every rank exited 0 — the job is done.
    ``resumable``  at least one rank DELIBERATELY exited resumable
                   (75: a drain or a watchdog peer-death exit), and
                   every other non-zero exit is either 75 too or a
                   SIGNAL death (negative Popen returncode: SIGKILL'd
                   by the OOM killer, preempted before the handler
                   ran). A signal-killed rank never got to exit 75
                   itself, but its peers' watchdogs vouched for the
                   gang with their own 75s and its state is in the
                   committed checkpoint — the relaunch case. With NO
                   75 in the gang there is no such vouching: an
                   all-signal-death gang (a deterministic native
                   SIGSEGV, say) is ``fatal`` — under the default
                   unbudgeted config it would otherwise respawn
                   forever, unseen by the in-process breaker too (the
                   process died before Python could count anything).
    ``fatal``      anything else: a positive non-resumable status (a
                   crash the in-process supervisor refused to retry,
                   a usage error) or signal deaths with no resumable
                   witness. Relaunching would replay it — give up and
                   surface it.
    """
    if all(rc == EXIT_OK for rc in exit_codes):
        return "ok"
    if EXIT_RESUMABLE in exit_codes and all(
        rc in (EXIT_OK, EXIT_RESUMABLE) or rc < 0 for rc in exit_codes
    ):
        return "resumable"
    return "fatal"


def supervise_gang(
    run_gang,
    budget: RestartBudget,
    *,
    log=print,
    on_relaunch=None,
) -> int:
    """Drive ``run_gang()`` (-> list of per-rank exit codes) to
    completion under the restart budget. Resumable gangs relaunch while
    the budget grants; an exhausted budget or a fatal rank gives up
    loudly with the gang's worst status. ``on_relaunch(attempt)`` runs
    before each relaunch — the elastic hook (resize the gang, pick a
    new nprocs) lives there."""
    attempt = 0
    while True:
        attempt += 1
        codes = list(run_gang())
        verdict = gang_verdict(codes)
        if verdict == "ok":
            if attempt > 1:
                log(f"launcher: gang complete (attempt {attempt})")
            return EXIT_OK
        if verdict == "fatal":
            bad = [
                rc for rc in codes
                if rc != EXIT_OK and rc != EXIT_RESUMABLE
            ]
            log(
                f"launcher: GIVING UP — rank exit status(es) {bad} are "
                "not resumable (a crash the in-process supervisor "
                "refused to retry, or signal deaths with no resumable "
                "witness); not relaunching"
            )
            positive = [rc for rc in bad if rc > 0]
            return max(positive) if positive else EXIT_FAILED
        if not budget.spend():
            log(
                "launcher: GIVING UP — restart budget exhausted "
                f"({budget.max_per_window} relaunch(es) per "
                f"{budget.window_s:g}s window); a drain/death cycle "
                "this hot needs an operator, not another relaunch"
            )
            return EXIT_RESUMABLE
        log(
            f"launcher: gang exited resumable (attempt {attempt}, "
            f"budget {budget.used}/{budget.max_per_window or 'inf'} "
            "in window) — relaunching"
        )
        if on_relaunch is not None:
            on_relaunch(attempt)
