"""Deterministic fault injection: the proof harness for recovery paths.

A fault plan is a comma-separated list of ``kind@at`` terms (optionally
``kind@at=value``, optionally rank-targeted with ``:rank=K``), parsed
from the ``-faults`` CLI flag or the ``SINGA_TPU_FAULTS`` env var:

  crash@7          raise InjectedCrash at the step-7 boundary (before the
                   step runs) — exercises supervisor auto-resume
  sigterm@12       deliver a synthetic SIGTERM at the step-12 boundary —
                   exercises the preemption drain + resumable exit
  nanloss@5        poison step 5's batch with NaN — exercises the
                   divergence guard (skip / rollback policies)
  corrupt_ckpt@1   truncate the 1st checkpoint written (ordinal, 1-based,
                   between the save and the LATEST mark) — exercises
                   torn-save detection in the retention module
  torn_sidecar@1   truncate the replica engine's ``.server`` sidecar of
                   the 1st checkpoint written (same ordinal keying) —
                   exercises the sidecar commit markers: a save whose
                   protocol sidecar tore must never become LATEST
  slowstep@9=0.5   sleep 0.5 s at the step-9 boundary — exercises the
                   step-wall-clock watchdog
  async_torn_write@1  tear the 1st ASYNC checkpoint write (ordinal,
                   1-based) and kill its publication — the writer
                   "dies" mid-write, before validation/LATEST ever run.
                   Exercises the zero-stall pipeline's crash safety
                   (resilience/async_ckpt.py): LATEST must keep naming
                   the previous complete save
  wire_drop@3      drop the 3rd transport send's first attempt on the
                   wire (comm/faults.py; also wire_delay@K:ms=N,
                   wire_dup@K, wire_torn@K, wire_partition@K[=S]
                   [:peer=H]) — the K here is a message-send ORDINAL,
                   not a step: the socket transport's retry/redeliver/
                   tombstone verdicts are the drill target
  profile@20:steps=5  not a fault at all — the profiler TRIGGER rides
                   the same plumbing (step-keyed, fire-once, rank-
                   targetable, forces per-step boundaries): bracket
                   steps 20..25 with jax.profiler.start_trace/stop_trace
                   into <workspace>/xprof so per-op attribution is one
                   config knob away. ``steps`` defaults to 1 and is only
                   meaningful on profile terms.

A ``:rank=K`` qualifier scopes a term to ONE process of a multi-process
job — ``sigterm@12:rank=0`` preempts only rank 0 (its peers learn of it
through the coordinated drain, resilience/coord.py), ``crash@7:rank=1``
kills only rank 1 (its peers' liveness watchdog turns the resulting
hung collective into a resumable exit). Unqualified terms fire on every
rank, which is what single-process drills always did. A rank-qualified
term that does not match this process is left UNFIRED — it neither
fires nor burns its once-only budget on the wrong rank.

Multi-process jobs must receive the SAME plan string on EVERY rank —
that is the whole point of the rank qualifier. A plan's presence forces
per-step boundaries (context.per_step), so a plan passed to one rank
only would desync that rank's step/chunk cadence — and with it every
collective, including the coordinated-drain barrier — from its
plan-less peers.

Every fault fires exactly once per plan object. The supervisor owns ONE
plan across all restart attempts, so ``crash@7`` does not re-fire after
the auto-resumed run passes step 7 again — which is what makes
end-to-end recovery *testable* instead of merely asserted. Injection
happens at the trainer's step-boundary seams (trainer.py run loop /
train_one_batch / save), never inside jitted code, so a faulted run's
device programs are bit-identical to a clean run's.
"""

from __future__ import annotations

import dataclasses
import os


# this process's rank, resolved lazily at fire time (coord's helper
# only imports jax inside the call) so plan PARSING never imports jax
from .coord import process_index as _process_index


class FaultPlanError(ValueError):
    """The -faults string does not match the plan grammar."""


class InjectedCrash(RuntimeError):
    """The failure a ``crash@N`` fault raises at its step boundary."""


KINDS = (
    "crash",
    "sigterm",
    "nanloss",
    "corrupt_ckpt",
    "torn_sidecar",
    "slowstep",
    "async_torn_write",
    "profile",
    # live-rollout faults (serve/rollout.py): keyed on weight-ship
    # ORDINALS — `at` is the K-th weight_ship this HOST receives
    # (scope with :rank=K in multi-process drills). torn_weights tears
    # the staged artifact so the CRC rejects it (retry then quarantine,
    # serving uninterrupted); swap_die kills the host mid-stage
    # (tombstone -> failover per the wire path, rollout pauses)
    "torn_weights",
    "swap_die",
    # wire faults (comm/faults.py): keyed on message-send ORDINALS,
    # not steps — `at` is the K-th transport send this process makes.
    # ``wire_delay@K:ms=N`` stalls N ms; ``:peer=H`` scopes a term to
    # sends addressed to H (or names a partition's victim)
    "wire_drop",
    "wire_delay",
    "wire_dup",
    "wire_torn",
    "wire_partition",
)

#: kinds triggered by step number at the pre-step boundary seam
STEP_KINDS = ("crash", "sigterm", "slowstep", "profile")


def tear_file(path: str) -> None:
    """Simulate a torn write: truncate ``path`` to half its bytes (the
    proc_0 shard, for sharded checkpoint dirs). The ONE copy of the
    tearing logic — both the corrupt_ckpt fault (context.py) and the
    async_torn_write fault (async_ckpt.py) use it."""
    target = path
    if os.path.isdir(path):
        target = os.path.join(path, "proc_0.npz")
    try:
        size = os.path.getsize(target)
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))
    except OSError:
        pass


@dataclasses.dataclass
class FaultSpec:
    """One ``kind@at[=value][:ms=N][:steps=N][:peer=H][:rank=K]`` term;
    ``fired`` flips on injection. ``rank=None`` means every process;
    ``steps`` is the profile trigger's bracket length, ``ms`` the
    wire_delay stall, ``peer`` a wire term's target host (None
    elsewhere)."""

    kind: str
    at: int
    value: float | None = None
    rank: int | None = None
    steps: int | None = None
    ms: int | None = None
    peer: str | None = None
    fired: bool = False

    def __str__(self) -> str:
        v = "" if self.value is None else f"={self.value:g}"
        m = "" if self.ms is None else f":ms={self.ms}"
        s = "" if self.steps is None else f":steps={self.steps}"
        p = "" if self.peer is None else f":peer={self.peer}"
        r = "" if self.rank is None else f":rank={self.rank}"
        return f"{self.kind}@{self.at}{v}{m}{s}{p}{r}"


class FaultPlan:
    """A parsed, once-each fault schedule shared across restart attempts."""

    def __init__(self, specs: list[FaultSpec] | None = None):
        self.specs = list(specs or [])
        #: flight recorder (obs/recorder.py) — the supervisor wires it
        #: so EVERY firing becomes a telemetry event, no matter which
        #: seam fired it (step boundary, batch poisoning, writer thread)
        self.recorder = None

    @classmethod
    def parse(cls, text: str | None) -> "FaultPlan":
        specs: list[FaultSpec] = []
        for term in (text or "").split(","):
            term = term.strip()
            if not term:
                continue
            # qualifiers split off first: values are plain floats, so
            # every ':' starts a ":key=val" qualifier (rank=K, steps=N)
            body, *quals = term.split(":")
            rank = None
            steps = None
            ms = None
            peer = None
            for qual in quals:
                qkey, qsep, qval = qual.partition("=")
                if qkey not in ("rank", "steps", "ms", "peer") or not qsep:
                    raise FaultPlanError(
                        f"fault term {term!r}: unknown qualifier "
                        f"{qual!r} (expected ':rank=K', ':steps=N', "
                        "':ms=N' or ':peer=H')"
                    )
                if qkey == "peer":
                    if not qval:
                        raise FaultPlanError(
                            f"fault term {term!r}: empty peer name"
                        )
                    peer = qval
                    continue
                try:
                    qint = int(qval)
                except ValueError:
                    raise FaultPlanError(
                        f"fault term {term!r}: {qkey} {qval!r} is not "
                        "an integer"
                    ) from None
                if qkey == "rank":
                    if qint < 0:
                        raise FaultPlanError(
                            f"fault term {term!r}: negative rank"
                        )
                    rank = qint
                elif qkey == "ms":
                    if qint < 0:
                        raise FaultPlanError(
                            f"fault term {term!r}: negative ms"
                        )
                    ms = qint
                else:
                    if qint < 1:
                        raise FaultPlanError(
                            f"fault term {term!r}: steps must be >= 1"
                        )
                    steps = qint
            head, sep, val = body.partition("=")
            kind, sep2, at = head.partition("@")
            if not sep2:
                raise FaultPlanError(
                    f"fault term {term!r}: expected kind@step"
                )
            if kind not in KINDS:
                raise FaultPlanError(
                    f"fault term {term!r}: unknown kind {kind!r} "
                    f"(known: {', '.join(KINDS)})"
                )
            try:
                at_n = int(at)
            except ValueError:
                raise FaultPlanError(
                    f"fault term {term!r}: step {at!r} is not an integer"
                ) from None
            if at_n < 0:
                raise FaultPlanError(f"fault term {term!r}: negative step")
            value = None
            if sep:
                try:
                    value = float(val)
                except ValueError:
                    raise FaultPlanError(
                        f"fault term {term!r}: value {val!r} is not a number"
                    ) from None
            if steps is not None and kind != "profile":
                raise FaultPlanError(
                    f"fault term {term!r}: ':steps=N' only applies to "
                    "profile triggers"
                )
            if ms is not None and kind != "wire_delay":
                raise FaultPlanError(
                    f"fault term {term!r}: ':ms=N' only applies to "
                    "wire_delay terms"
                )
            if peer is not None and not kind.startswith("wire_"):
                raise FaultPlanError(
                    f"fault term {term!r}: ':peer=H' only applies to "
                    "wire_* terms"
                )
            specs.append(FaultSpec(kind, at_n, value, rank, steps, ms, peer))
        return cls(specs)

    def __bool__(self) -> bool:
        return bool(self.specs)

    def fire(self, kind: str, at: int, *, peer: str | None = None
             ) -> FaultSpec | None:
        """The unfired ``kind@at`` spec, marked fired — or None.

        Rank-qualified specs only fire on their target process; on any
        other rank they stay unfired (the qualifier scopes the fault,
        it must not be consumed by the ranks it skips). ``peer``-
        qualified wire specs likewise fire only when the caller's
        ``peer`` (the send's destination) matches."""
        for spec in self.specs:
            if spec.kind != kind or spec.at != at or spec.fired:
                continue
            if spec.rank is not None and spec.rank != _process_index():
                continue
            if (
                spec.peer is not None and peer is not None
                and spec.peer != peer
            ):
                continue
            spec.fired = True
            # profile is documented as NOT a fault — it gets its own
            # profile_start/profile_stop events (context.py), and must
            # not inflate a trace summary's fired-fault count
            if self.recorder is not None and kind != "profile":
                # corrupt_ckpt/async_torn_write key on save ORDINALS,
                # not steps — those events inherit the last stamped step
                step_keyed = kind in STEP_KINDS or kind == "nanloss"
                self.recorder.event(
                    "fault",
                    step=at if step_keyed else None,
                    fault=str(spec),
                    fault_kind=kind,
                    at=at,
                )
            return spec
        return None

    def unfired(self) -> list[FaultSpec]:
        return [s for s in self.specs if not s.fired]

    def __str__(self) -> str:
        return ",".join(str(s) for s in self.specs) or "<empty>"
