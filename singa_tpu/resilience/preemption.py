"""Preemption handling: SIGTERM/SIGINT -> drain -> resumable exit.

TPU maintenance events and cluster preemptions arrive as SIGTERM with a
short grace window. The discipline here is the TensorFlow/TPU one: the
handler only sets a flag; the train loop checks it at step/chunk
boundaries, drains whatever is in flight, writes a final checkpoint, and
exits with a distinct "resumable" status (EXIT_RESUMABLE, EX_TEMPFAIL's
75) so the launcher can tell "re-run me" from a real failure. Nothing
asynchronous ever touches training state.
"""

from __future__ import annotations

import signal
import threading


#: clean finish
EXIT_OK = 0
#: crashed and the supervisor gave up (or no supervision requested)
EXIT_FAILED = 1
#: drained on SIGTERM/SIGINT with state checkpointed — safe to relaunch
#: (BSD sysexits EX_TEMPFAIL: "transient failure, retry")
EXIT_RESUMABLE = 75


class PreemptionDrained(Exception):
    """Raised at a step boundary after the drain checkpoint is written;
    the supervisor converts it into EXIT_RESUMABLE."""

    def __init__(self, step: int, checkpoint: str | None):
        super().__init__(f"preempted at step {step}")
        self.step = step
        self.checkpoint = checkpoint


class PreemptionHandler:
    """Flag-only signal handler for SIGTERM/SIGINT.

    ``install()`` swaps the handlers in (restoring the previous ones on
    ``uninstall()``); ``trigger()`` is the synthetic path fault injection
    uses — same flag, no real signal, fully deterministic. Installation
    degrades gracefully off the main thread (signal.signal raises there):
    the synthetic path still works, real signals keep their previous
    behavior.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._event = threading.Event()
        self._prev: dict[int, object] = {}
        self.reason: str | None = None

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def trigger(self, reason: str) -> None:
        self.reason = reason
        self._event.set()

    def _handle(self, signum, frame) -> None:
        del frame
        self.trigger(f"signal {signal.Signals(signum).name}")

    def install(self) -> bool:
        """-> True when real signal handlers are in place."""
        try:
            for sig in self.SIGNALS:
                self._prev[sig] = signal.signal(sig, self._handle)
            return True
        except ValueError:  # not the main thread
            self._prev.clear()
            return False

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
