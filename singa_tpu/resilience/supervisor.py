"""The supervised train loop: crash -> restore newest checkpoint -> retry.

``run()`` owns the whole training lifetime the way the reference's
parameter-server tier owned model state (src/main.cc:49-55 — a restarted
worker group could rejoin and refetch): with no server tier, the
supervisor is the trainer-side replacement. Per attempt it locates the
newest *complete* checkpoint (resilience/retention.py — the LATEST
marker, falling back over torn saves), points the model config at it,
rebuilds the trainer, and runs. Failures restart with bounded
exponential backoff; a crash-loop circuit breaker gives up loudly after
``max_restarts`` consecutive failures that each made less than
``restart_window_steps`` steps of progress. SIGTERM/SIGINT surface as
``PreemptionDrained`` (state already checkpointed) and exit with the
distinct resumable status code so launchers can tell "relaunch me" from
"debug me".

Jobs with no ``resilience`` config block and no fault plan take a
transparent single-attempt path — exactly the pre-supervisor behavior.
"""

from __future__ import annotations

import os
import time

from ..config.schema import ModelConfig, ResilienceConfig
from . import retention
from .context import ResilienceContext
from .faults import FaultPlan
from .guard import GuardGaveUp
from .preemption import (
    EXIT_OK,
    EXIT_RESUMABLE,
    PreemptionDrained,
)


def _banner(trainer, model_cfg: ModelConfig) -> None:
    trainer.log(
        f"training {model_cfg.name!r}: steps "
        f"[{trainer.start_step}, {model_cfg.train_steps}), "
        f"batch {trainer.train_net.batchsize}, mesh {dict(trainer.mesh.shape)}"
    )


def _record_start(rec, trainer, model_cfg, attempt: int, resumed) -> None:
    """The run_start event: run identity (run id = config hash), where
    this attempt begins, and the topology a post-mortem needs."""
    from .coord import process_count

    rec.event(
        "run_start",
        step=trainer.start_step,
        attempt=attempt,
        name=model_cfg.name,
        train_steps=model_cfg.train_steps,
        batch=trainer.train_net.batchsize,
        mesh={k: int(v) for k, v in dict(trainer.mesh.shape).items()},
        nprocs=process_count(),
        pid=os.getpid(),
        resumed_from=resumed,
    )
    rec.flush()


def run(
    model_cfg: ModelConfig,
    cluster_cfg=None,
    *,
    seed: int = 0,
    faults: str | FaultPlan | None = None,
    log=print,
    trainer_factory=None,
    **trainer_kwargs,
) -> int:
    """Train ``model_cfg`` to completion under supervision; returns the
    process exit code (EXIT_OK / EXIT_RESUMABLE). A crash the circuit
    breaker refuses to retry propagates — loudly — to the caller."""
    if trainer_factory is None:
        from ..trainer import make_trainer as trainer_factory
    plan = (
        faults if isinstance(faults, FaultPlan) else FaultPlan.parse(faults)
    )
    trainer_kwargs.setdefault("log", log)

    # flight recorder (singa_tpu/obs/): always-on when the job has a
    # workspace to write into; one recorder spans every restart attempt
    # so the per-rank event log is the whole job's story
    from ..obs.recorder import recorder_for_job

    rec = recorder_for_job(model_cfg, cluster_cfg, log=log)

    res = model_cfg.resilience
    if res is None and not plan:
        # unsupervised jobs keep their exact pre-supervisor behavior
        trainer = trainer_factory(
            model_cfg, cluster_cfg, seed=seed, **trainer_kwargs
        )
        _banner(trainer, model_cfg)
        if rec is None:
            trainer.run()
            return EXIT_OK
        trainer.attach_telemetry(rec)
        _record_start(rec, trainer, model_cfg, attempt=1, resumed=None)
        try:
            trainer.run()
            rec.event(
                "run_stop", step=model_cfg.train_steps,
                status="ok", exit_code=EXIT_OK,
            )
            return EXIT_OK
        except BaseException as e:
            rec.event(
                "run_stop", status="crashed",
                error=f"{type(e).__name__}: {e}",
            )
            raise
        finally:
            rec.close()

    if res is None:
        res = ResilienceConfig()
    ctx = ResilienceContext(res, plan, log=log, recorder=rec)
    if not ctx.preemption.install():
        log(
            "resilience: cannot install signal handlers (not the main "
            "thread) — synthetic/injected preemption only"
        )
    ckpt_dir = None
    if cluster_cfg is not None and cluster_cfg.workspace:
        ckpt_dir = os.path.join(cluster_cfg.workspace, "checkpoints")
    configured_ckpt = model_cfg.checkpoint
    failures = 0  # consecutive low-progress failures (the breaker's count)
    attempt = 0
    try:
        while True:
            attempt += 1
            if attempt > 1:
                # a restart re-jits the very programs this process just
                # cached; same-process executable re-reads can crash
                # jaxlib (utils/compile_cache.py) — run restarts uncached
                from ..utils.compile_cache import disable_compile_cache

                disable_compile_cache(log)
            # land any async write the crashed attempt left in flight:
            # the restart must resume from the newest save that actually
            # finished publishing, not race the writer for it (no-op on
            # the synchronous path; errors already logged by the writer)
            ctx.flush_async(raise_errors=False)
            # auto-resume: the newest complete checkpoint beats the
            # config's warm-start path; a torn/corrupt newest save falls
            # back to the one before it (retention.resolve_latest)
            latest = retention.resolve_latest(ckpt_dir)
            model_cfg.checkpoint = latest or configured_ckpt
            # elastic restore: a sharded save written by a DIFFERENT
            # world size is not an error — the trainer reshards it onto
            # this topology (resilience/reshard.py). Announce it here,
            # before the rebuild, so a post-mortem can see the N->M
            # transition even if the restore itself then fails
            if model_cfg.checkpoint:
                from .coord import process_count
                from .reshard import checkpoint_nprocs

                saved_np = checkpoint_nprocs(model_cfg.checkpoint)
                if saved_np is not None and saved_np != process_count():
                    log(
                        f"supervisor: elastic restore — "
                        f"{model_cfg.checkpoint} was written by "
                        f"{saved_np} process(es), this job runs "
                        f"{process_count()}; resharding on load"
                    )
                    if rec is not None:
                        rec.event(
                            "reshard",
                            checkpoint=model_cfg.checkpoint,
                            saved_nprocs=saved_np,
                            nprocs=process_count(),
                            attempt=attempt,
                        )
            trainer = None
            try:
                trainer = trainer_factory(
                    model_cfg, cluster_cfg, seed=seed, **trainer_kwargs
                )
                ctx.bind(trainer)
                _banner(trainer, model_cfg)
                if rec is not None:
                    _record_start(
                        rec, trainer, model_cfg,
                        attempt=attempt, resumed=latest,
                    )
                trainer.run()
                # the end-of-run checkpoint must be durable before the
                # job reports success (raises if the write failed)
                ctx.flush_async()
                log(
                    f"supervisor: training complete at step "
                    f"{model_cfg.train_steps} (attempt {attempt})"
                )
                # deliberate exit: peers must not read our now-frozen
                # heartbeat as a death (watchdog.py done sentinel)
                ctx.mark_done()
                if rec is not None:
                    rec.event(
                        "run_stop", step=model_cfg.train_steps,
                        status="ok", exit_code=EXIT_OK, attempt=attempt,
                    )
                return EXIT_OK
            except PreemptionDrained as e:
                log(
                    f"supervisor: preempted at step {e.step} — "
                    f"exiting resumable (status {EXIT_RESUMABLE})"
                )
                if ctx.coordinated_exit:
                    # every rank drained at this same step (or there is
                    # only one) — a deliberate exit, not a death
                    ctx.mark_done()
                if rec is not None:
                    # the exit-75 record post-mortems key on: this rank
                    # left deliberately, resumable, at this step
                    rec.event(
                        "run_stop", step=e.step, status="preempted",
                        exit_code=EXIT_RESUMABLE, attempt=attempt,
                        checkpoint=e.checkpoint,
                    )
                return EXIT_RESUMABLE
            except GuardGaveUp as e:
                # a deterministic divergence replays identically after
                # every restore — restarting would loop forever (each
                # attempt makes nominal step progress before tripping,
                # so the breaker alone would keep re-arming)
                log(
                    f"supervisor: GIVING UP — divergence guard declared "
                    f"the failure unrecoverable ({e}); not restarting"
                )
                if rec is not None:
                    rec.event(
                        "run_stop", status="guard_gave_up",
                        attempt=attempt, error=str(e),
                    )
                raise
            except Exception as e:  # the supervisor survives ANY crash
                start = trainer.start_step if trainer is not None else 0
                done = (
                    getattr(trainer, "completed_steps", start)
                    if trainer is not None
                    else start
                )
                progress = max(0, done - start)
                window = max(1, res.restart_window_steps)
                if progress >= window:
                    failures = 0  # real progress re-arms the breaker
                failures += 1
                log(
                    f"supervisor: attempt {attempt} died at step {done} "
                    f"({type(e).__name__}: {e}); {progress} step(s) of "
                    "progress since restore"
                )
                if rec is not None:
                    rec.event(
                        "crash", step=done, attempt=attempt,
                        error=f"{type(e).__name__}: {e}",
                        progress=progress,
                    )
                from .coord import process_count

                if process_count() > 1:
                    # a single rank restarting in-process would rejoin
                    # peers whose collectives are steps ahead — they
                    # can never re-align. Exit resumable instead: the
                    # cluster launcher restarts EVERY rank from the
                    # newest complete checkpoint (our peers' liveness
                    # watchdogs turn their hung collectives into the
                    # same resumable exit). NOT mark_done: peers must
                    # see this exit as the death it is.
                    log(
                        "supervisor: multi-process job — skipping "
                        "in-process restart (peers' collectives would "
                        f"desync); exiting resumable ({EXIT_RESUMABLE}) "
                        "so the launcher restarts all ranks together"
                    )
                    if rec is not None:
                        rec.event(
                            "run_stop", step=done, status="crashed",
                            exit_code=EXIT_RESUMABLE, attempt=attempt,
                        )
                    return EXIT_RESUMABLE
                if failures > res.max_restarts:
                    log(
                        "supervisor: GIVING UP — "
                        f"{failures} failure(s), each with fewer than "
                        f"{window} step(s) of progress "
                        f"(max_restarts {res.max_restarts}); re-raising"
                    )
                    if rec is not None:
                        rec.event(
                            "run_stop", step=done, status="gave_up",
                            attempt=attempt, failures=failures,
                        )
                    raise
                delay = min(
                    res.backoff_max,
                    res.backoff_base * (2 ** (failures - 1)),
                )
                log(
                    f"supervisor: restart {failures}/{res.max_restarts} "
                    f"in {delay:g}s"
                )
                if rec is not None:
                    # restart with cause and backoff — flushed now: the
                    # next attempt may die before its display cadence
                    rec.event(
                        "restart", step=done, attempt=attempt,
                        failures=failures, backoff_s=delay,
                        cause=f"{type(e).__name__}: {e}",
                    )
                    rec.flush()
                if delay > 0:
                    time.sleep(delay)
    finally:
        ctx.stop()
        ctx.preemption.uninstall()
        model_cfg.checkpoint = configured_ckpt
        if rec is not None:
            rec.close()
