"""Cross-process coordination plane: the cluster half of resilience.

The reference survived process loss because the parameter server was
the durable truth and workers handshook through Router PING/PONG
barriers (src/utils/router.cc:16-86). singa-tpu has no server tier, so
the coordination obligations move here, shaped by TPU-pod preemption
semantics (maintenance SIGTERMs arrive per-host; a collective that
loses any peer hangs forever instead of crashing):

  preemption_barrier   fold each host's local preemption flag into a
      cross-host OR — one tiny allgather at step/chunk-boundary cadence
      (the loop's existing sync points; never inside a step) so ANY
      host's SIGTERM makes EVERY host drain at the SAME step boundary,
      write its shard of the drain checkpoint, and exit resumable (75)
      together. The launcher then restarts all ranks from one
      consistent step. Chandy-Lamport in miniature: the OR-ed flag is
      the marker, the step boundary is the consistent cut.

  commit markers       the two-phase commit for sharded async saves.
      Phase 1: every process publishes its ``proc_k.npz`` shard and
      then a CRC'd ``commit_k.json`` marker (atomic tmp+rename, so a
      marker is either absent or complete). Phase 2: process 0 promotes
      ``LATEST`` only after ``await_commits`` observes every marker and
      verifies each against its shard's bytes. A missed deadline
      degrades to an EXPLICIT "torn — keep the previous LATEST"
      verdict, never to judging the save early with whatever shards
      happen to exist (the bug the old filesystem poll had).
      ``retention._sharded_valid`` checks the same markers on the
      restore side, so a half-committed save is never resumable.

The serving fleet (singa_tpu/serve/fleet/) rides the same two
disciplines at its own grain: its mailbox transport publishes every
message and status file through ``atomic_write_bytes`` below (a
message is absent or complete, never torn — the commit markers'
contract), and a SIGTERM'd fleet host drains at a tick boundary and
exits EXIT_RESUMABLE exactly like a training rank — except its
in-flight sequences route to a PEER host over the migration path
instead of only handing back to the launcher.

No imports from the trainer package, and retention must be able to
import this module (not the other way round).
"""

from __future__ import annotations

import json
import os
import time
import zlib


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Publish ``data`` at ``path`` atomically (pid-suffixed tmp +
    rename): a reader can observe the file absent or complete, never
    torn-but-parseable. The primitive under the commit markers below
    AND the fleet mailbox's message/status files."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path

#: manifest field value declaring "this save carries commit markers"
COMMIT_VERSION = 2
#: format tag inside each marker file
COMMIT_FORMAT = "singa-tpu-commit-v2"


def process_count() -> int:
    """Lazy jax.process_count() — 1 when jax is unavailable/uninitialized."""
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# coordinated preemption drain
# ---------------------------------------------------------------------------


def preemption_barrier(requested: bool) -> bool:
    """Cross-host OR of this host's preemption flag.

    Called at step/chunk boundaries (every rank calls it at the SAME
    boundaries — the cadence loop is deterministic), so the allgather
    doubles as the consistent cut: when it returns True on one rank it
    returns True on all of them, and every rank drains at this exact
    step. Single-process jobs short-circuit to the local flag."""
    if process_count() <= 1:
        return bool(requested)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray(bool(requested), np.int32)
    )
    return bool(np.asarray(flags).any())


# ---------------------------------------------------------------------------
# two-phase sharded-save commit
# ---------------------------------------------------------------------------


def commit_marker_path(path: str, proc: int) -> str:
    """``commit_k.json`` inside sharded checkpoint dir ``path``."""
    return os.path.join(path, f"commit_{proc}.json")


def shard_digest(shard_file: str) -> dict:
    """{"size", "crc32"} over the shard file's full byte stream."""
    crc = 0
    size = 0
    with open(shard_file, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {"size": size, "crc32": crc & 0xFFFFFFFF}


def write_commit(path: str, proc: int) -> str:
    """Publish process ``proc``'s commit marker for the shard it just
    wrote (phase 1 of the two-phase commit). Atomic tmp+rename: a
    marker can be absent or complete, never torn-but-parseable."""
    marker = {
        "format": COMMIT_FORMAT,
        "proc": int(proc),
        **shard_digest(os.path.join(path, f"proc_{proc}.npz")),
    }
    return atomic_write_bytes(
        commit_marker_path(path, proc),
        json.dumps(marker).encode("utf-8"),
    )


def commit_ok(path: str, proc: int) -> bool:
    """True iff process ``proc``'s commit marker exists, parses, and
    matches its shard's bytes (size + CRC32). Any tear — of the marker
    OR of the shard after the marker was written (the corrupt_ckpt /
    async_torn_write faults) — fails here."""
    try:
        with open(commit_marker_path(path, proc), encoding="utf-8") as f:
            marker = json.load(f)
    except (OSError, ValueError):
        return False
    if marker.get("format") != COMMIT_FORMAT:
        return False
    if int(marker.get("proc", -1)) != int(proc):
        return False
    try:
        digest = shard_digest(os.path.join(path, f"proc_{proc}.npz"))
    except OSError:
        return False
    try:
        return (
            int(marker["size"]) == digest["size"]
            and int(marker["crc32"]) == digest["crc32"]
        )
    except (KeyError, TypeError, ValueError):
        return False


def sidecar_path(path: str) -> str:
    """The replica engine's ``.server`` sidecar beside checkpoint
    ``path`` (center + protocol snapshot, trainer/replica.py)."""
    return path + ".server"


def sidecar_marker_path(path: str) -> str:
    """``commit_server.json`` INSIDE sharded checkpoint dir ``path`` —
    the sidecar's commit marker. Living inside the dir means retention
    fingerprints cover it and rmtree removes it with the save."""
    return os.path.join(path, "commit_server.json")


def write_sidecar_commit(path: str) -> str:
    """Publish the commit marker for the ``.server`` sidecar the
    replica engine just wrote beside sharded dir ``path`` (the same
    size+CRC32 vouching as the per-proc markers, atomic tmp+rename).
    Written AFTER the sidecar, by the one rank that writes sidecars
    (rank 0): marker present => sidecar fully written."""
    marker = {
        "format": COMMIT_FORMAT,
        "sidecar": True,
        **shard_digest(sidecar_path(path)),
    }
    return atomic_write_bytes(
        sidecar_marker_path(path),
        json.dumps(marker).encode("utf-8"),
    )


def sidecar_commit_ok(path: str) -> bool:
    """True iff sharded dir ``path``'s ``.server`` sidecar exists and
    matches its commit marker's size + CRC32. A torn sidecar, a torn
    marker, or a rank that died between sidecar and marker all fail —
    a committed shard save can never pair with a half-written protocol
    sidecar (retention._sharded_valid enforces this whenever the
    manifest promises a sidecar)."""
    try:
        with open(sidecar_marker_path(path), encoding="utf-8") as f:
            marker = json.load(f)
    except (OSError, ValueError):
        return False
    if marker.get("format") != COMMIT_FORMAT or not marker.get("sidecar"):
        return False
    try:
        digest = shard_digest(sidecar_path(path))
    except OSError:
        return False
    try:
        return (
            int(marker["size"]) == digest["size"]
            and int(marker["crc32"]) == digest["crc32"]
        )
    except (KeyError, TypeError, ValueError):
        return False


def await_commits(
    path: str, timeout: float = 60.0, log=print, poll: float = 0.05
) -> bool:
    """Phase 2, run by process 0 before promoting ``LATEST``: wait for
    every manifest-promised commit marker to EXIST. Byte verification
    (marker CRC vs shard) is deliberately NOT done here — it happens
    exactly once, in ``retention.validate_checkpoint``, which the
    caller runs next; verifying here too would read every shard's full
    bytes twice per save on process 0's promotion path.

    A marker is atomic (tmp+rename after its shard), so existence is
    the only thing that can legitimately lag — bounded by ``timeout``.
    Past the deadline the save is judged torn — explicitly, loudly —
    and LATEST keeps naming the previous complete checkpoint. Never
    judges early with whatever shards happen to exist."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        log(
            f"COMMIT: {path} has no readable manifest — "
            "treating the save as torn"
        )
        return False
    if manifest.get("commit") != COMMIT_VERSION:
        # pre-commit-protocol dir: nothing to await; retention's CRC
        # walk remains the only defense
        return True
    nprocs = int(manifest.get("nprocs", 1))
    deadline = time.monotonic() + max(0.0, timeout)
    while True:
        missing = [
            k
            for k in range(nprocs)
            if not os.path.exists(commit_marker_path(path, k))
        ]
        if not missing:
            break
        if time.monotonic() >= deadline:
            log(
                f"COMMIT: deadline ({timeout:g}s) expired waiting for "
                f"commit marker(s) {missing} in {path} — judging the "
                "save TORN; LATEST keeps the previous complete "
                "checkpoint"
            )
            return False
        time.sleep(poll)
    return True
