"""Fault-tolerance runtime: supervised auto-resume, preemption-safe
checkpointing, a divergence guard, and deterministic fault injection.

The reference's availability story was structural — a parameter-server
tier held the global model so a restarted worker group could rejoin
(src/main.cc:49-55), and Worker::Resume was a declared-but-empty TODO
(src/worker/worker.cc:65-67). singa-tpu has no server tier, so the
obligation moves into this trainer-side resilience layer:

  supervisor.py   the supervised train loop (crash -> restore newest
                  complete checkpoint -> bounded-backoff retry ->
                  crash-loop circuit breaker)
  retention.py    keep-last-N + atomic LATEST marker + torn-save defense
  preemption.py   SIGTERM/SIGINT -> drain -> final checkpoint ->
                  resumable exit code (EXIT_RESUMABLE, 75)
  guard.py        on-device loss/grad-norm finiteness verdict with
                  skip / rollback-with-LR-backoff policies — zero
                  per-step host syncs
  async_ckpt.py   zero-stall checkpointing — non-blocking device
                  snapshot at the step boundary + a double-buffered
                  background writer publishing through retention's
                  atomic LATEST (``async_checkpoint: true``)
  coord.py        the cross-process coordination plane: coordinated
                  preemption drain (any host's SIGTERM -> every host
                  drains at the SAME step and exits 75 together) and
                  the two-phase commit markers for sharded saves
  watchdog.py     step-wall-clock watchdog (hung-collective detection)
                  + per-rank heartbeat files with a peer-liveness
                  deadline — a dead peer turns a forever-hung
                  collective into a loud resumable exit
  reshard.py      elastic restore: reshard an N-process sharded
                  checkpoint onto M ranks (box-intersection re-slicing
                  per target shard; the ``hostable`` mesh-admission
                  check netlint ELA001 mirrors)
  launcher.py     launcher-side restart budget (resumable exits bypass
                  the in-process breaker by design; the budget bounds
                  gang relaunches per rolling window) + the elastic
                  gang-relaunch loop behind tools/elastic_launch.py
  faults.py       the deterministic fault plan (``crash@7,...``, with
                  an optional ``:rank=K`` target) that lets tests
                  PROVE end-to-end recovery
  context.py      ResilienceContext — what the trainer's step-boundary
                  seams actually call

Config: the ``resilience { ... }`` block (config/schema.py
ResilienceConfig); CLI: ``-faults`` / ``SINGA_TPU_FAULTS`` on
``python -m singa_tpu.main``, which routes every job through the
supervisor. ``supervisor`` itself is imported lazily (it pulls in the
trainer package) — use ``from singa_tpu.resilience import supervisor``.
"""

from . import coord  # noqa: F401
from .async_ckpt import AsyncCheckpointer, AsyncWriteError  # noqa: F401
from .context import ResilienceContext  # noqa: F401
from .faults import (  # noqa: F401
    FaultPlan,
    FaultPlanError,
    FaultSpec,
    InjectedCrash,
    tear_file,
)
from .guard import (  # noqa: F401
    GUARD_BAD,
    GUARD_CONSEC,
    GUARD_KEYS,
    GUARD_LR,
    GuardGaveUp,
    GuardSpec,
    init_guard_buffers,
)
from .preemption import (  # noqa: F401
    EXIT_FAILED,
    EXIT_OK,
    EXIT_RESUMABLE,
    PreemptionDrained,
    PreemptionHandler,
)
from .launcher import (  # noqa: F401
    RestartBudget,
    gang_verdict,
    supervise_gang,
)
from .reshard import (  # noqa: F401
    Resharder,
    ReshardError,
    check_manifest,
    checkpoint_nprocs,
    hostable,
)
from .retention import (  # noqa: F401
    LATEST_MARKER,
    apply_retention,
    gc_stale_shards,
    mark_latest,
    resolve_latest,
    validate_checkpoint,
)
from .watchdog import Watchdog  # noqa: F401
