"""ResilienceContext: the object the trainer's step-boundary seams call.

One context lives for a whole supervised job (across restart attempts —
that is what makes the fault plan fire-once and the watchdog/preemption
state coherent). The trainer holds it as ``trainer.resilience`` and
calls exactly four seams, all host-side, all outside jitted code:

  before_step(trainer, step)    watchdog heartbeat; crash/sigterm/
                                slowstep fault injection; preemption
                                drain (save + PreemptionDrained)
  after_step(trainer, step)     guard rollback policy (counter read at
                                most once per rollback window — never
                                per step); returns the possibly
                                rolled-back step to continue from
  inject_batch_faults(...)      nanloss poisoning of one step's batch
  checkpoint_written(...)       corrupt_ckpt fault; validation; LATEST
                                marking; keep-last-N retention

A trainer with ``resilience = None`` (the default) skips all of it.
"""

from __future__ import annotations

import os
import time

import jax.numpy as jnp

from ..config.schema import ResilienceConfig
from . import coord, retention
from .async_ckpt import AsyncCheckpointer
from .faults import FaultPlan, InjectedCrash, tear_file
from .guard import GUARD_CONSEC, GUARD_LR, GuardGaveUp
from .preemption import PreemptionDrained, PreemptionHandler
from .watchdog import Watchdog


class ResilienceContext:
    def __init__(
        self,
        res_cfg: ResilienceConfig | None = None,
        plan: FaultPlan | None = None,
        log=print,
    ):
        self.cfg = res_cfg if res_cfg is not None else ResilienceConfig()
        self.plan = plan if plan is not None else FaultPlan()
        self.log = log
        self.preemption = PreemptionHandler()
        self.watchdog = Watchdog(self.cfg.watchdog_timeout, log)
        #: zero-stall checkpoint pipeline (resilience/async_ckpt.py);
        #: None = the synchronous save path. ONE writer across restart
        #: attempts, like the fault plan — ordinals stay coherent.
        self.async_ckpt = (
            AsyncCheckpointer(plan=self.plan, log=log)
            if self.cfg.async_checkpoint
            else None
        )
        #: guard rollbacks performed (surfaced in the display line)
        self.rollbacks = 0
        #: <workspace>/checkpoints, once a trainer with a workspace binds
        self.ckpt_dir: str | None = None
        #: 1-based ordinal of checkpoint saves (corrupt_ckpt@K keys on it)
        self.save_ordinal = 0
        self._last_guard_check = -(10**9)
        #: rollback livelock defense: consecutive rollbacks that never
        #: got past the step that tripped the previous one
        self._stuck_rollbacks = 0
        self._rollback_high_step = -1
        #: process count, refreshed at bind (jax is initialized by then)
        self._nprocs = 1

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def per_step(self) -> bool:
        """Fault plans need exact step boundaries: the trainer disables
        multi-step chunking for the whole drill (fired or not — a drill
        run stays deterministic over chunk throughput)."""
        return bool(self.plan)

    @property
    def coordinated_exit(self) -> bool:
        """True when this rank's drain is guaranteed to be cluster-wide
        (single process, or the coordinated drain is on) — i.e. when a
        drained exit may publish the 'deliberate exit' sentinel without
        stranding peers in a collective."""
        return self._nprocs <= 1 or bool(self.cfg.coordinate_preemption)

    def bind(self, trainer) -> None:
        """Attach to a (possibly restarted) trainer instance."""
        trainer.resilience = self
        self.ckpt_dir = trainer._checkpoint_dir()
        self._nprocs = coord.process_count()
        # peer-liveness heartbeats (watchdog.py): each rank's watchdog
        # thread touches <workspace>/heartbeats/rank_k.hb while the
        # process lives; a peer file stale past heartbeat_timeout_s
        # while OUR step is stalled turns a forever-hung collective
        # into a loud resumable exit
        if (
            self.cfg.heartbeat_timeout_s > 0
            and self._nprocs > 1
            and trainer.cluster is not None
            and trainer.cluster.workspace
        ):
            self.watchdog.enable_heartbeats(
                os.path.join(trainer.cluster.workspace, "heartbeats"),
                rank=coord.process_index(),
                nprocs=self._nprocs,
                peer_timeout=self.cfg.heartbeat_timeout_s,
            )
        self.watchdog.beat(trainer.start_step)
        self.watchdog.start()

    def mark_done(self) -> None:
        """A deliberate exit (training complete, or a coordinated
        drain): publish the done sentinel so peers' liveness watchdogs
        never read our frozen heartbeat as a death."""
        self.watchdog.mark_done()

    def stop(self) -> None:
        self.watchdog.stop()
        if self.async_ckpt is not None:
            self.async_ckpt.stop()

    def flush_async(self, raise_errors: bool = True) -> None:
        """Durability barrier: block until every submitted async
        checkpoint write is on disk and published. No-op when the
        synchronous path is in use."""
        if self.async_ckpt is not None:
            self.async_ckpt.flush(raise_errors=raise_errors)

    # ------------------------------------------------------------------
    # step-boundary seams
    # ------------------------------------------------------------------

    def before_step(self, trainer, step: int) -> None:
        self.watchdog.beat(step)
        spec = self.plan.fire("slowstep", step)
        if spec is not None:
            dur = 1.0 if spec.value is None else spec.value
            self.log(f"FAULT: slowstep@{step} — stalling {dur:g}s")
            time.sleep(dur)
        spec = self.plan.fire("sigterm", step)
        if spec is not None:
            self.log(f"FAULT: sigterm@{step} — synthetic SIGTERM")
            self.preemption.trigger(f"injected sigterm@{step}")
        spec = self.plan.fire("crash", step)
        if spec is not None:
            self.log(f"FAULT: crash@{step} — raising InjectedCrash")
            raise InjectedCrash(f"injected crash@{step}")
        requested = self.preemption.requested
        if self.cfg.coordinate_preemption and self._nprocs > 1:
            # coordinated drain (resilience/coord.py): fold every
            # host's flag into a cross-host OR at this boundary — one
            # tiny allgather riding the loop's existing sync cadence —
            # so any host's SIGTERM drains EVERY host at THIS step
            requested = coord.preemption_barrier(requested)
            if requested and not self.preemption.requested:
                self.preemption.trigger(
                    "coordinated drain (a peer host was preempted)"
                )
        if requested:
            self._drain(trainer, step)

    def _drain(self, trainer, step: int) -> None:
        """Write the final checkpoint and leave with resumable status.
        Called at a step boundary, so nothing is in flight — the current
        step/chunk has fully drained."""
        path = None
        if self.cfg.preemption_checkpoint:
            path = trainer.save(step)
            # the final checkpoint must be DURABLE before exit 75 — the
            # launcher may relaunch the moment the process dies
            self.flush_async()
        where = (
            f", final checkpoint {path}"
            if path
            else ", no workspace configured — state not checkpointed"
        )
        self.log(
            f"PREEMPTION: {self.preemption.reason} — drained at "
            f"step {step}{where}; exiting resumable"
        )
        raise PreemptionDrained(step, path)

    def after_step(self, trainer, step: int) -> int:
        """Guard rollback policy. The counter read is a host sync, so it
        runs at most once per rollback window (and once at the end of
        the run), never per step."""
        self.watchdog.beat(step)
        g = trainer._guard
        if g is None or g.policy != "kRollback":
            return step
        due = step - self._last_guard_check >= g.rollback_after
        if not due and step < trainer.cfg.train_steps:
            return step
        self._last_guard_check = step
        consec = int(trainer.buffers[GUARD_CONSEC])
        if consec < g.rollback_after:
            return step
        return self._rollback(trainer, step, consec)

    def _rollback(self, trainer, step: int, consec: int) -> int:
        g = trainer._guard
        # livelock defense: a rollback restores params, stream
        # positions, AND the per-step RNG folds exactly — a
        # deterministic divergence (NaN baked into the data) replays
        # identically no matter how far the LR backs off. Rolling back
        # again without ever getting PAST the previous trigger step can
        # therefore never converge; give up loudly instead of burning
        # the reservation in silence.
        if step > self._rollback_high_step:
            self._stuck_rollbacks = 1
        else:
            self._stuck_rollbacks += 1
        self._rollback_high_step = max(self._rollback_high_step, step)
        limit = max(2, self.cfg.max_restarts)
        if self._stuck_rollbacks > limit:
            raise GuardGaveUp(
                f"{self._stuck_rollbacks} rollbacks without progress "
                f"past step {self._rollback_high_step} — the divergence "
                "replays deterministically; refusing to livelock"
            )
        new_scale = float(trainer.buffers[GUARD_LR]) * g.lr_backoff
        # land any in-flight async write first: the rollback should
        # restore the NEWEST complete checkpoint, not race its publish
        self.flush_async(raise_errors=False)
        path = retention.resolve_latest(self.ckpt_dir)
        if path is None:
            self.log(
                f"GUARD: {consec} consecutive bad steps at step {step} "
                "but no checkpoint to roll back to — resetting the "
                f"counter and backing the LR scale off to {new_scale:g}"
            )
            trainer.set_guard_state(consec=0, lr_scale=new_scale)
            return step
        self.log(
            f"GUARD: {consec} consecutive bad steps at step {step} — "
            f"rolling back to {path}, LR scale -> {new_scale:g}"
        )
        rolled = trainer.rollback_to(path)
        self.rollbacks += 1
        trainer.set_guard_state(consec=0, lr_scale=new_scale)
        # re-arm the window from the rollback point so the next check
        # happens a full window after training resumes
        self._last_guard_check = rolled
        return rolled

    def inject_batch_faults(self, trainer, step: int, batch: dict) -> dict:
        """nanloss@step: poison the batch with NaN images (labels keep
        their values). Device-cached ``__idx__`` feeds are materialized
        to direct feeds first — the poisoned step takes the plain path."""
        if self.plan.fire("nanloss", step) is None:
            return batch
        self.log(f"FAULT: nanloss@{step} — poisoning the step's batch")
        out = {}
        for name, feed in batch.items():
            if "__idx__" in feed:
                # idx may be multi-dim — the replica engine gathers a
                # (replicas, batch) grid; the poisoned feed keeps every
                # leading index axis so the vmapped step maps it as-is
                idx = feed["__idx__"]
                shape = tuple(idx.shape) + tuple(feed["image"].shape[1:])
                labels = jnp.take(feed["label"], idx, axis=0)
            else:
                shape = tuple(feed["image"].shape)
                labels = feed["label"]
            out[name] = {
                "image": jnp.full(shape, jnp.nan, dtype=jnp.float32),
                "label": labels,
            }
        return out

    # ------------------------------------------------------------------
    # checkpoint hook
    # ------------------------------------------------------------------

    def checkpoint_written(self, trainer, path: str, step: int) -> None:
        del trainer, step
        self.save_ordinal += 1
        spec = self.plan.fire("corrupt_ckpt", self.save_ordinal)
        if spec is not None:
            tear_file(path)
            self.log(
                f"FAULT: corrupt_ckpt@{self.save_ordinal} — tore {path}"
            )
        # validation, LATEST, and retention are process 0's job alone:
        # every process racing rmtree/marker writes on the same dir
        # would be chaos. For sharded saves, promotion is the second
        # phase of the commit protocol (resilience/coord.py): wait for
        # every rank's CRC'd commit_k marker, verify each against its
        # shard, and on a missed deadline judge the save TORN — never
        # early, never with whatever shards happen to exist.
        if coord.process_index() != 0:
            return
        committed = True
        if os.path.isdir(path):
            committed = coord.await_commits(
                path, timeout=self.cfg.commit_timeout_s, log=self.log
            )
        folder = os.path.dirname(path)
        if committed and retention.validate_checkpoint(path):
            retention.mark_latest(folder, path)
        else:
            self.log(
                f"WARNING: checkpoint {path} failed validation — "
                "LATEST keeps pointing at the previous complete save"
            )
        if self.cfg.keep_last > 0:
            for gone in retention.apply_retention(folder, self.cfg.keep_last):
                self.log(f"retention: removed {gone}")

