"""ResilienceContext: the object the trainer's step-boundary seams call.

One context lives for a whole supervised job (across restart attempts —
that is what makes the fault plan fire-once and the watchdog/preemption
state coherent). The trainer holds it as ``trainer.resilience`` and
calls exactly four seams, all host-side, all outside jitted code:

  before_step(trainer, step)    watchdog heartbeat; crash/sigterm/
                                slowstep fault injection; preemption
                                drain (save + PreemptionDrained)
  after_step(trainer, step)     guard rollback policy (counter read at
                                most once per rollback window — never
                                per step); returns the possibly
                                rolled-back step to continue from
  inject_batch_faults(...)      nanloss poisoning of one step's batch
  checkpoint_written(...)       corrupt_ckpt fault; validation; LATEST
                                marking; keep-last-N retention

A trainer with ``resilience = None`` (the default) skips all of it.
"""

from __future__ import annotations

import os
import time

import jax.numpy as jnp

from ..config.schema import ResilienceConfig
from . import coord, retention
from .async_ckpt import AsyncCheckpointer
from .faults import FaultPlan, InjectedCrash, tear_file
from .guard import GUARD_CONSEC, GUARD_LR, GuardGaveUp
from .preemption import PreemptionDrained, PreemptionHandler
from .watchdog import Watchdog


class ResilienceContext:
    def __init__(
        self,
        res_cfg: ResilienceConfig | None = None,
        plan: FaultPlan | None = None,
        log=print,
        recorder=None,
    ):
        self.cfg = res_cfg if res_cfg is not None else ResilienceConfig()
        self.plan = plan if plan is not None else FaultPlan()
        self.log = log
        #: flight recorder (obs/recorder.py); None = telemetry off. The
        #: plan shares it so every fault firing is an event regardless
        #: of which seam fired it.
        self.recorder = recorder
        self.plan.recorder = recorder
        #: profile@K trigger state (jax.profiler bracket)
        self._profiling = False
        self._profile_stop_at: int | None = None
        self._profile_dir: str | None = None
        self.preemption = PreemptionHandler()
        self.watchdog = Watchdog(self.cfg.watchdog_timeout, log)
        #: zero-stall checkpoint pipeline (resilience/async_ckpt.py);
        #: None = the synchronous save path. ONE writer across restart
        #: attempts, like the fault plan — ordinals stay coherent.
        self.async_ckpt = (
            AsyncCheckpointer(plan=self.plan, log=log)
            if self.cfg.async_checkpoint
            else None
        )
        #: guard rollbacks performed (surfaced in the display line)
        self.rollbacks = 0
        #: <workspace>/checkpoints, once a trainer with a workspace binds
        self.ckpt_dir: str | None = None
        #: 1-based ordinal of checkpoint saves (corrupt_ckpt@K keys on it)
        self.save_ordinal = 0
        self._last_guard_check = -(10**9)
        #: rollback livelock defense: consecutive rollbacks that never
        #: got past the step that tripped the previous one
        self._stuck_rollbacks = 0
        self._rollback_high_step = -1
        #: process count, refreshed at bind (jax is initialized by then)
        self._nprocs = 1

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def per_step(self) -> bool:
        """Fault plans need exact step boundaries: the trainer disables
        multi-step chunking for the whole drill (fired or not — a drill
        run stays deterministic over chunk throughput)."""
        return bool(self.plan)

    @property
    def coordinated_exit(self) -> bool:
        """True when this rank's drain is guaranteed to be cluster-wide
        (single process, or the coordinated drain is on) — i.e. when a
        drained exit may publish the 'deliberate exit' sentinel without
        stranding peers in a collective."""
        return self._nprocs <= 1 or bool(self.cfg.coordinate_preemption)

    def bind(self, trainer) -> None:
        """Attach to a (possibly restarted) trainer instance."""
        trainer.resilience = self
        self.ckpt_dir = trainer._checkpoint_dir()
        self._nprocs = coord.process_count()
        if self.recorder is not None:
            # one recorder spans restart attempts, like the fault plan;
            # each trainer incarnation re-wires its timers' span sink
            trainer.attach_telemetry(self.recorder)
            self.watchdog.recorder = self.recorder
            if self.async_ckpt is not None:
                self.async_ckpt.recorder = self.recorder
        #: where profile@K traces land (resolved at bind: needs the
        #: trainer's cluster workspace + telemetry block)
        self._profile_dir = None
        if trainer.cluster is not None and trainer.cluster.workspace:
            tel = getattr(trainer.cfg, "telemetry", None)
            sub = tel.profile_subfolder if tel is not None else "xprof"
            self._profile_dir = os.path.join(
                trainer.cluster.workspace, sub
            )
        # peer-liveness heartbeats (watchdog.py): each rank's watchdog
        # thread touches <workspace>/heartbeats/rank_k.hb while the
        # process lives; a peer file stale past heartbeat_timeout_s
        # while OUR step is stalled turns a forever-hung collective
        # into a loud resumable exit
        if (
            self.cfg.heartbeat_timeout_s > 0
            and self._nprocs > 1
            and trainer.cluster is not None
            and trainer.cluster.workspace
        ):
            self.watchdog.enable_heartbeats(
                os.path.join(trainer.cluster.workspace, "heartbeats"),
                rank=coord.process_index(),
                nprocs=self._nprocs,
                peer_timeout=self.cfg.heartbeat_timeout_s,
            )
        self.watchdog.beat(trainer.start_step)
        self.watchdog.start()

    def mark_done(self) -> None:
        """A deliberate exit (training complete, or a coordinated
        drain): publish the done sentinel so peers' liveness watchdogs
        never read our frozen heartbeat as a death."""
        self.watchdog.mark_done()

    def stop(self) -> None:
        # a profile bracket the run never reached the end of (early
        # drain, crash, train_steps inside the window) still writes its
        # trace out instead of vanishing with the process
        self._stop_profile(None)
        self.watchdog.stop()
        if self.async_ckpt is not None:
            self.async_ckpt.stop()

    def flush_async(self, raise_errors: bool = True) -> None:
        """Durability barrier: block until every submitted async
        checkpoint write is on disk and published. No-op when the
        synchronous path is in use."""
        if self.async_ckpt is not None:
            self.async_ckpt.flush(raise_errors=raise_errors)

    # ------------------------------------------------------------------
    # step-boundary seams
    # ------------------------------------------------------------------

    def before_step(self, trainer, step: int) -> None:
        self.watchdog.beat(step)
        if self.recorder is not None:
            self.recorder.step = step  # cheap attribute stamp, no I/O
        # profile@K[:steps=N] trigger (obs): stop first — a bracket
        # ending at THIS boundary must close before a new one opens —
        # then start, so the jax.profiler trace covers exactly the
        # steps [K, K+N) that run after this seam
        if self._profile_stop_at is not None and step >= self._profile_stop_at:
            self._stop_profile(step)
        spec = self.plan.fire("profile", step)
        if spec is not None:
            self._start_profile(step, spec)
        spec = self.plan.fire("slowstep", step)
        if spec is not None:
            dur = 1.0 if spec.value is None else spec.value
            self.log(f"FAULT: slowstep@{step} — stalling {dur:g}s")
            time.sleep(dur)
        spec = self.plan.fire("sigterm", step)
        if spec is not None:
            self.log(f"FAULT: sigterm@{step} — synthetic SIGTERM")
            self.preemption.trigger(f"injected sigterm@{step}")
        spec = self.plan.fire("crash", step)
        if spec is not None:
            self.log(f"FAULT: crash@{step} — raising InjectedCrash")
            raise InjectedCrash(f"injected crash@{step}")
        local = self.preemption.requested
        requested = local
        if self.cfg.coordinate_preemption and self._nprocs > 1:
            # coordinated drain (resilience/coord.py): fold every
            # host's flag into a cross-host OR at this boundary — one
            # tiny allgather riding the loop's existing sync cadence —
            # so any host's SIGTERM drains EVERY host at THIS step
            requested = coord.preemption_barrier(requested)
            if requested and self.recorder is not None:
                # the barrier outcome, per rank: `local` tells a
                # post-mortem which host was actually signalled and
                # which learned of it through the OR
                self.recorder.event(
                    "drain_barrier", step=step,
                    local=bool(local), cluster=True,
                )
            if requested and not self.preemption.requested:
                self.preemption.trigger(
                    "coordinated drain (a peer host was preempted)"
                )
        if requested:
            self._drain(trainer, step)

    def _drain(self, trainer, step: int) -> None:
        """Write the final checkpoint and leave with resumable status.
        Called at a step boundary, so nothing is in flight — the current
        step/chunk has fully drained."""
        # close any open profiler bracket first: the trace must land on
        # disk before the process exits 75
        self._stop_profile(step)
        path = None
        if self.cfg.preemption_checkpoint:
            path = trainer.save(step)
            # the final checkpoint must be DURABLE before exit 75 — the
            # launcher may relaunch the moment the process dies
            self.flush_async()
        where = (
            f", final checkpoint {path}"
            if path
            else ", no workspace configured — state not checkpointed"
        )
        self.log(
            f"PREEMPTION: {self.preemption.reason} — drained at "
            f"step {step}{where}; exiting resumable"
        )
        if self.recorder is not None:
            self.recorder.event(
                "drain", step=step,
                reason=self.preemption.reason, checkpoint=path,
            )
            # the process is about to exit — the drain record must not
            # die in the buffer
            self.recorder.flush()
        raise PreemptionDrained(step, path)

    def after_step(self, trainer, step: int) -> int:
        """Guard rollback policy. The counter read is a host sync, so it
        runs at most once per rollback window (and once at the end of
        the run), never per step."""
        self.watchdog.beat(step)
        g = trainer._guard
        if g is None or g.policy != "kRollback":
            return step
        due = step - self._last_guard_check >= g.rollback_after
        if not due and step < trainer.cfg.train_steps:
            return step
        self._last_guard_check = step
        consec = int(trainer.buffers[GUARD_CONSEC])
        if consec < g.rollback_after:
            return step
        return self._rollback(trainer, step, consec)

    def _rollback(self, trainer, step: int, consec: int) -> int:
        g = trainer._guard
        # livelock defense: a rollback restores params, stream
        # positions, AND the per-step RNG folds exactly — a
        # deterministic divergence (NaN baked into the data) replays
        # identically no matter how far the LR backs off. Rolling back
        # again without ever getting PAST the previous trigger step can
        # therefore never converge; give up loudly instead of burning
        # the reservation in silence.
        if step > self._rollback_high_step:
            self._stuck_rollbacks = 1
        else:
            self._stuck_rollbacks += 1
        self._rollback_high_step = max(self._rollback_high_step, step)
        limit = max(2, self.cfg.max_restarts)
        if self._stuck_rollbacks > limit:
            raise GuardGaveUp(
                f"{self._stuck_rollbacks} rollbacks without progress "
                f"past step {self._rollback_high_step} — the divergence "
                "replays deterministically; refusing to livelock"
            )
        new_scale = float(trainer.buffers[GUARD_LR]) * g.lr_backoff
        # land any in-flight async write first: the rollback should
        # restore the NEWEST complete checkpoint, not race its publish
        self.flush_async(raise_errors=False)
        path = retention.resolve_latest(self.ckpt_dir)
        if path is None:
            self.log(
                f"GUARD: {consec} consecutive bad steps at step {step} "
                "but no checkpoint to roll back to — resetting the "
                f"counter and backing the LR scale off to {new_scale:g}"
            )
            if self.recorder is not None:
                self.recorder.event(
                    "guard_rollback", step=step, consecutive_bad=consec,
                    checkpoint=None, lr_scale=new_scale,
                )
            trainer.set_guard_state(consec=0, lr_scale=new_scale)
            return step
        self.log(
            f"GUARD: {consec} consecutive bad steps at step {step} — "
            f"rolling back to {path}, LR scale -> {new_scale:g}"
        )
        rolled = trainer.rollback_to(path)
        self.rollbacks += 1
        if self.recorder is not None:
            # verdict detail: what tripped (consecutive non-finite
            # steps), where training rewound to, the compounded backoff
            self.recorder.event(
                "guard_rollback", step=step, consecutive_bad=consec,
                checkpoint=path, resumed_step=rolled, lr_scale=new_scale,
                rollbacks=self.rollbacks,
            )
            self.recorder.flush()
        trainer.set_guard_state(consec=0, lr_scale=new_scale)
        # re-arm the window from the rollback point so the next check
        # happens a full window after training resumes
        self._last_guard_check = rolled
        return rolled

    def inject_batch_faults(self, trainer, step: int, batch: dict) -> dict:
        """nanloss@step: poison the batch with NaN images (labels keep
        their values). Device-cached ``__idx__`` feeds are materialized
        to direct feeds first — the poisoned step takes the plain path."""
        if self.plan.fire("nanloss", step) is None:
            return batch
        self.log(f"FAULT: nanloss@{step} — poisoning the step's batch")
        out = {}
        for name, feed in batch.items():
            if "__idx__" in feed:
                # idx may be multi-dim — the replica engine gathers a
                # (replicas, batch) grid; the poisoned feed keeps every
                # leading index axis so the vmapped step maps it as-is
                idx = feed["__idx__"]
                shape = tuple(idx.shape) + tuple(feed["image"].shape[1:])
                labels = jnp.take(feed["label"], idx, axis=0)
            else:
                shape = tuple(feed["image"].shape)
                labels = feed["label"]
            out[name] = {
                "image": jnp.full(shape, jnp.nan, dtype=jnp.float32),
                "label": labels,
            }
        return out

    # ------------------------------------------------------------------
    # profiler trigger (profile@K[:steps=N] — obs plane)
    # ------------------------------------------------------------------

    def _start_profile(self, step: int, spec) -> None:
        """Open a jax.profiler bracket over steps [step, step+N). Rides
        the fault-plan plumbing, so it is fire-once, rank-targetable,
        and forces the per-step boundaries that make the bracket
        exact. Degrades to a logged no-op when the profiler (or a
        workspace to write into) is unavailable."""
        if self._profiling:
            self.log(
                f"PROFILE: trigger at step {step} ignored — a trace is "
                "already running"
            )
            return
        if not self._profile_dir:
            self.log(
                f"PROFILE: trigger at step {step} ignored — no "
                "workspace configured for the trace directory"
            )
            return
        nsteps = spec.steps if spec.steps is not None else 1
        try:
            import jax.profiler

            os.makedirs(self._profile_dir, exist_ok=True)
            jax.profiler.start_trace(self._profile_dir)
        except Exception as e:  # profiler availability is host-dependent
            self.log(
                f"PROFILE: could not start jax.profiler trace "
                f"({type(e).__name__}: {e}) — continuing unprofiled"
            )
            return
        self._profiling = True
        self._profile_stop_at = step + nsteps
        self.log(
            f"PROFILE: tracing steps [{step}, {step + nsteps}) -> "
            f"{self._profile_dir}"
        )
        if self.recorder is not None:
            self.recorder.event(
                "profile_start", step=step,
                stop_at=step + nsteps, dir=self._profile_dir,
            )

    def _stop_profile(self, step: int | None) -> None:
        """Close the open bracket (if any); ``step=None`` marks a
        lifecycle close (drain / run end) rather than the scheduled
        boundary."""
        self._profile_stop_at = None
        if not self._profiling:
            return
        self._profiling = False
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:
            self.log(
                f"PROFILE: stop_trace failed ({type(e).__name__}: {e})"
            )
            return
        where = f"at step {step}" if step is not None else "at shutdown"
        self.log(f"PROFILE: trace stopped {where} -> {self._profile_dir}")
        if self.recorder is not None:
            self.recorder.event(
                "profile_stop", step=step, dir=self._profile_dir,
            )

    # ------------------------------------------------------------------
    # checkpoint hook
    # ------------------------------------------------------------------

    def checkpoint_written(self, trainer, path: str, step: int) -> None:
        del trainer
        self.save_ordinal += 1
        spec = self.plan.fire("corrupt_ckpt", self.save_ordinal)
        if spec is not None:
            tear_file(path)
            self.log(
                f"FAULT: corrupt_ckpt@{self.save_ordinal} — tore {path}"
            )
        spec = self.plan.fire("torn_sidecar", self.save_ordinal)
        if spec is not None:
            # the replica .server sidecar beside the save (written just
            # before this hook): tear IT, not the shards — validation
            # must reject the whole save on the sidecar alone
            tear_file(path + ".server")
            self.log(
                f"FAULT: torn_sidecar@{self.save_ordinal} — tore "
                f"{path}.server"
            )
        rec = self.recorder
        if rec is not None:
            # every rank records its own write (async path: from the
            # writer thread — the recorder is thread-safe); for sharded
            # saves the rank's commit marker (phase 1 of the two-phase
            # commit) is already on disk at this point
            payload = {"path": path, "ordinal": self.save_ordinal}
            if os.path.isdir(path):
                payload["commit_marker"] = os.path.exists(
                    coord.commit_marker_path(path, coord.process_index())
                )
            rec.event("ckpt_written", step=step, **payload)
        # validation, LATEST, and retention are process 0's job alone:
        # every process racing rmtree/marker writes on the same dir
        # would be chaos. For sharded saves, promotion is the second
        # phase of the commit protocol (resilience/coord.py): wait for
        # every rank's CRC'd commit_k marker, verify each against its
        # shard, and on a missed deadline judge the save TORN — never
        # early, never with whatever shards happen to exist.
        if coord.process_index() != 0:
            return
        committed = True
        if os.path.isdir(path):
            committed = coord.await_commits(
                path, timeout=self.cfg.commit_timeout_s, log=self.log
            )
            if rec is not None:
                rec.event(
                    "ckpt_commit", step=step, path=path,
                    ok=bool(committed),
                )
        folder = os.path.dirname(path)
        if committed and retention.validate_checkpoint(path):
            retention.mark_latest(folder, path)
            if rec is not None:
                rec.event("ckpt_latest", step=step, path=path)
        else:
            self.log(
                f"WARNING: checkpoint {path} failed validation — "
                "LATEST keeps pointing at the previous complete save"
            )
            if rec is not None:
                rec.event("ckpt_invalid", step=step, path=path)
        if self.cfg.keep_last > 0:
            for gone in retention.apply_retention(folder, self.cfg.keep_last):
                self.log(f"retention: removed {gone}")

