"""lm_32k phase attribution: where the model-vs-kernel MFU gap lives.

VERDICT r4 #7: lm_32k model-level MFU (21.6%) trails the streamed
kernel's standalone 49.1 TF/s (24.9% of peak) with no accounting of the
non-attention tail. This harness produces the same three-way split the
S=8192 regime got:

  1. full tinylm step at S=32768, batch 1 (bench.py lm_32k methodology);
  2. the same step with BOTH attention layers monkeypatched to identity
     -> the non-attention tail's direct time;
  3. the flash kernel standalone at the model's exact shape
     (batch 1, 4 heads, d=64, S=32768, causal, fwd+bwd x2 blocks).

Run (reserves the chip):  python bench/ablations/lm32k_tail.py
"""

import os
import sys
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp


def model_step_ms(identity_attn: bool) -> float:
    """bench.py lm_32k two-window slope, optionally with attention
    layers passing their input through (params still exist; the QKV/out
    projections vanish with the scores — the measured tail is the
    embed/LN/FFN/head/loss remainder)."""
    import bench
    from singa_tpu.layers import sequence as seq_mod

    orig = seq_mod.AttentionLayer.apply
    if identity_attn:
        seq_mod.AttentionLayer.apply = (
            lambda self, params, inputs, *, training, rng=None: inputs[0]
        )
    try:
        w = bench.bench_lm_32k()
    finally:
        seq_mod.AttentionLayer.apply = orig
    return w["step_ms"]


def kernel_ms(s=32768, heads=4, d=64, nblocks=2) -> float:
    """Standalone flash f+b at the model's shape, scan-slope."""
    from singa_tpu.ops.attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv, kd = jax.random.split(key, 4)
    q = jax.random.normal(kq, (1, heads, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (1, heads, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (1, heads, s, d), jnp.bfloat16)
    dy = jax.random.normal(kd, (1, heads, s, d), jnp.bfloat16)

    def one(args):
        q, k, v = args

        def f(q, k, v):
            out = q
            for _ in range(nblocks):
                out = flash_attention(out, k, v, True)
            return jnp.vdot(out.astype(jnp.float32), dy.astype(jnp.float32))

        val, (dq, dk, dv) = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return (
            q + dq.astype(q.dtype) * jnp.bfloat16(1e-6),
            k + dk.astype(k.dtype) * jnp.bfloat16(1e-6),
            v + dv.astype(v.dtype) * jnp.bfloat16(1e-6),
        )

    def loop(args, n):
        def body(c, _):
            return one(c), None

        out, _ = jax.lax.scan(body, args, None, length=n)
        return out

    n1, n2 = 4, 12
    j1 = jax.jit(lambda a: loop(a, n1))
    j2 = jax.jit(lambda a: loop(a, n2))
    args = (q, k, v)
    jax.block_until_ready(j1(args))
    jax.block_until_ready(j2(args))
    best = {}
    for name, j in (("n1", j1), ("n2", j2)):
        best[name] = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(j(args))
            best[name] = min(best[name], time.perf_counter() - t0)
    return (best["n2"] - best["n1"]) / (n2 - n1) * 1e3


def main():
    print(f"device: {jax.devices()[0].device_kind}")
    full = model_step_ms(identity_attn=False)
    tail = model_step_ms(identity_attn=True)
    kern = kernel_ms()
    print(f"full lm_32k step:            {full:7.2f} ms")
    print(f"attention->identity (tail):  {tail:7.2f} ms")
    print(f"implied in-model attention:  {full - tail:7.2f} ms")
    print(f"standalone kernel (2 calls): {kern:7.2f} ms")


if __name__ == "__main__":
    main()
