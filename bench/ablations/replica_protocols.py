"""Replica-protocol engine ablation on the 8-virtual-device geometry.

The BASELINE.md r3/r4 engine-comparison methodology, now with the
RandomSync ratios the protocol actually exists for (the reference's
bandwidth throttle SUBSAMPLES coordinates, param_manager.cc:85-93;
ratio 1.0 is the degenerate case its fast path special-cases away):

  sync Trainer           batch 512 over 8 devices
  Elastic                8 replicas x 64, sync_freq 8
  RandomSync ratio 1.0   dense-prefix fast path (no index tensors)
  RandomSync ratio 0.5   sampled path
  RandomSync ratio 0.1   sampled path

Both partial-coverage formulations are timed at each ratio: the dense
parallel prefix (O(R*n) transient) and the bounded-memory serial scan
(what production uses when R*n exceeds DENSE_PREFIX_MAX_ELEMS —
singa_tpu/parallel/consistency.py).

Run (takes ~2 min on the 1-core CI host):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python bench/ablations/replica_protocols.py
"""

import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

CONF = """
name: "ablate-mlp"
train_steps: 4096
test_steps: 0
display_frequency: 0
updater {{
  base_learning_rate: 0.05
  momentum: 0.9
  type: kSGD
  param_type: "{param_type}"
  moving_rate: {moving_rate}
  sync_frequency: 8
  warmup_steps: 8
}}
neuralnet {{
  layer {{ name: "data" type: "kShardData"
    data_param {{ path: "{shard}" batchsize: {batch} }} }}
  layer {{ name: "mnist" type: "kMnistImage" srclayers: "data"
    mnist_param {{ norm_a: 127.5 norm_b: 1 }} }}
  layer {{ name: "label" type: "kLabel" srclayers: "data" }}
  layer {{ name: "fc1" type: "kInnerProduct" srclayers: "mnist"
    inner_product_param {{ num_output: 64 }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }} }}
  layer {{ name: "tanh1" type: "kTanh" srclayers: "fc1" }}
  layer {{ name: "fc2" type: "kInnerProduct" srclayers: "tanh1"
    inner_product_param {{ num_output: 10 }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }} }}
  layer {{ name: "loss" type: "kSoftmaxLoss" srclayers: "fc2" srclayers: "label"
    softmaxloss_param {{ topk: 1 }} }}
}}
"""


def _cfg(shard, param_type="Param", batch=512, moving_rate=0.3):
    from singa_tpu.config import parse_model_config

    return parse_model_config(
        CONF.format(
            shard=shard, param_type=param_type, batch=batch,
            moving_rate=moving_rate,
        )
    )


def _time_steps(trainer, n1=128, n2=512):
    """Two-window slope (bench.py methodology): marginal s/step."""
    import jax.numpy as jnp

    def sync():
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    def run(s0, n):
        s = s0
        while s < s0 + n:
            take = min(
                trainer._chunk_cap(), trainer._chunk_len(s), s0 + n - s
            )
            if take > 1:
                trainer.train_chunk(s, take)
            else:
                trainer.train_one_batch(s)
            s += take

    run(0, n1)
    run(n1, n2)
    sync()
    best, step = {}, n1 + n2
    for n in (n1, n2):
        best[n] = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            run(step, n)
            sync()
            best[n] = min(best[n], time.perf_counter() - t0)
            step += n
    return (best[n2] - best[n1]) / (n2 - n1)


def bench_sync(shard):
    from singa_tpu.trainer import Trainer

    t = Trainer(
        _cfg(shard), seed=0, log=lambda s: None, prefetch=False
    )
    return _time_steps(t)


def bench_replica(shard, protocol, ratio=1.0):
    """ReplicaTrainer with the protocol; for RandomSync the ratio is
    FORCED after bootstrap (the bandwidth-adaptive SyncConfig would
    otherwise pick it from wall-clock noise)."""
    from singa_tpu.trainer import ReplicaTrainer

    moving = 0.3 if protocol == "Elastic" else 0.0
    t = ReplicaTrainer(
        _cfg(shard, param_type=protocol, batch=64, moving_rate=moving),
        seed=0, log=lambda s: None, prefetch=False,
    )
    # drive through warmup + bootstrap, then pin the ratio before the
    # lazily-built sync jit compiles
    for s in range(t.warmup_steps):
        t.train_one_batch(s)
    assert t._bootstrapped and t._sync_jit is None
    t.sample_ratio = ratio

    def run_from(s0, n):
        s = s0
        while s < s0 + n:
            take = min(t._chunk_cap(), t._chunk_len(s), s0 + n - s)
            if take > 1:
                t.train_chunk(s, take)
            else:
                t.train_one_batch(s)
            s += take

    import jax.numpy as jnp

    def sync():
        return float(jnp.sum(jnp.abs(next(iter(t.params.values())))))

    n1, n2 = 128, 512
    run_from(t.warmup_steps, n1 + n2)
    sync()
    best, step = {}, t.warmup_steps + n1 + n2
    for n in (n1, n2):
        best[n] = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            run_from(step, n)
            sync()
            best[n] = min(best[n], time.perf_counter() - t0)
            step += n
    return (best[n2] - best[n1]) / (n2 - n1)


ROWS = [
    # (label, kind, protocol, ratio, dense_budget or None=default)
    ("sync Trainer (batch 512 / 8 dev)", "sync", None, None, None),
    ("ReplicaTrainer, Elastic (sync_freq 8)", "rep", "Elastic", None, None),
    ("ReplicaTrainer, RandomSync ratio 1.0 (dense fast path)",
     "rep", "RandomSync", 1.0, None),
    ("ReplicaTrainer, RandomSync ratio 0.5 (dense prefix)",
     "rep", "RandomSync", 0.5, None),
    ("ReplicaTrainer, RandomSync ratio 0.5 (bounded scan)",
     "rep", "RandomSync", 0.5, 0),
    ("ReplicaTrainer, RandomSync ratio 0.1 (dense prefix)",
     "rep", "RandomSync", 0.1, None),
    ("ReplicaTrainer, RandomSync ratio 0.1 (bounded scan)",
     "rep", "RandomSync", 0.1, 0),
]


def run_row(shard, kind, protocol, ratio, budget):
    if budget is not None:
        from singa_tpu.parallel import consistency

        consistency.DENSE_PREFIX_MAX_ELEMS = budget
    if kind == "sync":
        return bench_sync(shard)
    return bench_replica(shard, protocol, ratio if ratio else 1.0)


def main():
    """Each row runs in its own subprocess: one long-lived process
    accumulating 7 jitted programs on this 1-core host starves the
    8 virtual device threads into XLA's collective-rendezvous timeout
    (observed: AllGather 'stuck' dumps after row 3)."""
    import json
    import subprocess

    from singa_tpu.data.loader import synthetic_arrays, write_records

    tmp = tempfile.mkdtemp(prefix="singa_ablate_")
    shard = os.path.join(tmp, "shard")
    write_records(shard, *synthetic_arrays(1024, seed=1))

    rows = []
    for label, kind, protocol, ratio, budget in ROWS:
        spec = json.dumps([shard, kind, protocol, ratio, budget])
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row", spec],
            capture_output=True, text=True, timeout=600,
        )
        if out.returncode:
            print(f"{label}: FAILED\n{out.stderr}", file=sys.stderr)
            rows.append((label, None))
        else:
            rows.append((label, float(out.stdout.strip().splitlines()[-1])))

    s_sync = rows[0][1]
    print(f"{'engine':58s}  ms/step  vs sync")
    for name, s in rows:
        if s is None:
            print(f"{name:58s}   FAILED")
        else:
            ratio = f"{s / s_sync:5.2f}x" if s_sync else "  n/a"
            print(f"{name:58s}  {s * 1e3:7.2f}  {ratio}")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--row":
        import json

        shard, kind, protocol, ratio, budget = json.loads(sys.argv[2])
        print(run_row(shard, kind, protocol, ratio, budget))
        sys.exit(0)
    main()
