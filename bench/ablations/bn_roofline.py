"""BatchNorm roofline on the chip: what can the stats pass ever give back?

VERDICT r4 #2 allows two outcomes for ResNet-50's BN cost (marginal
14.6 ms by layer ablation): close the gap to an estimated ~11 ms floor,
or measure that the stats pass is irreducible under XLA's fusion model.
This harness grounds that choice in real numbers:

  * ResNet-50's 53 BN instances touch 2.71 GB of bf16 activations per
    pass. The fused op's information-theoretic minimum is 8 touches
    (fwd: stats read, normalize read+write; bwd: reduction read of
    (dy, x), dx-pass read of (dy, x) + write) = 21.7 GB = 26.5 ms at
    the v5e's 819 GB/s — ABOVE the measured marginal cost. XLA already
    shares reads with neighboring fusions (conv-bwd reads the same x
    and dy); the r4 "~11 ms floor" arithmetic was mis-derived
    (5 x 2.9 GB / 819 GB/s = 17.7 ms, not 11).
  * The stats pass itself is ONE touch: 2.71 GB = 3.3 ms at peak.
    A perfect conv-epilogue stats kernel (two-phase conv+BN Pallas,
    which would mean reimplementing conv) can recover AT MOST that:
    46.6 ms -> 43.3 ms = 34.8% MFU. The >=35% bar is out of reach by
    same-math scheduling — hence the opt-in subsample-stats knob.

The microbench below measures the standalone fused op against a pure
elementwise chain of the same byte count, with CSE/constant-folding
defeated (distinct inputs per instance, random cotangents, dx carried).

Run (reserves the chip):  python bench/ablations/bn_roofline.py
"""

import os
import sys
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from singa_tpu.ops.norm import batch_norm_train

# (shape, count) — ResNet-50 batch-128 BN instances (BASELINE.md r5)
SHAPES = [
    ((128, 64, 112, 112), 1),
    ((128, 64, 56, 56), 6),
    ((128, 256, 56, 56), 4),
    ((128, 128, 28, 28), 8),
    ((128, 512, 28, 28), 5),
    ((128, 256, 14, 14), 12),
    ((128, 1024, 14, 14), 7),
    ((128, 512, 7, 7), 6),
    ((128, 2048, 7, 7), 4),
]


def _slope(fn, args, n1=10, n2=30):
    def loop(args, n):
        def body(c, _):
            return fn(c), None

        out, _ = jax.lax.scan(body, args, None, length=n)
        return out

    j1 = jax.jit(lambda a: loop(a, n1))
    j2 = jax.jit(lambda a: loop(a, n2))
    jax.block_until_ready(j1(args))
    jax.block_until_ready(j2(args))
    best = {}
    for name, j, n in (("n1", j1, n1), ("n2", j2, n2)):
        best[name] = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(j(args))
            best[name] = min(best[name], time.perf_counter() - t0)
    return (best["n2"] - best["n1"]) / (n2 - n1)


def make_args():
    """One distinct (x, dy) pair PER INSTANCE (53 total) so CSE cannot
    collapse repeated instances of a shape."""
    key = jax.random.PRNGKey(0)
    xs, dys, gs, bs = [], [], [], []
    for shape, cnt in SHAPES:
        for i in range(cnt):
            key, k1, k2 = jax.random.split(key, 3)
            xs.append(jax.random.normal(k1, shape, jnp.bfloat16))
            dys.append(jax.random.normal(k2, shape, jnp.bfloat16))
    for shape, cnt in SHAPES:
        for _ in range(cnt):
            gs.append(jnp.ones((shape[1],), jnp.bfloat16))
            bs.append(jnp.zeros((shape[1],), jnp.bfloat16))
    return xs, dys, gs, bs


def bn_chain(args):
    """Per instance: y, vjp = vjp(bn, x); (dx,..) = vjp(random dy).
    Carry x' = dx + eps*y so BOTH outputs materialize and the next
    iteration is data-dependent (nothing hoists, nothing folds)."""
    xs, dys, gs, bs = args
    new_xs = []
    for x, dy, g, b in zip(xs, dys, gs, bs):
        def f(x, g, b):
            y, m, v = batch_norm_train(x, g, b, 1e-5, None)
            return y

        y, vjp = jax.vjp(f, x, g, b)
        dx, dg, db = vjp(dy)
        new_xs.append(dx + y * jnp.bfloat16(1e-6))
    return new_xs, dys, gs, bs


def elementwise_chain(args):
    """Same nominal byte count as the BN chain's 8 touches, pure
    elementwise: 4 passes of read(x)+read(dy)->write per instance
    (= 8 array touches of x-sized data), data-dependent carry."""
    xs, dys, gs, bs = args
    new_xs = []
    for x, dy in zip(xs, dys):
        acc = x
        for _ in range(2):
            acc = acc + dy * jnp.bfloat16(0.3)   # read acc, dy; write
            acc = acc * jnp.bfloat16(0.999) + x * jnp.bfloat16(1e-3)
        new_xs.append(acc)
    return new_xs, dys, gs, bs


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}")
    args = make_args()
    elems = sum(
        cnt * int(jnp.prod(jnp.array(s))) for s, cnt in SHAPES
    )
    gb = elems * 2 / 1e9  # one touch of every instance, bf16
    print(f"activation footprint: {gb:.2f} GB per touch, 53 instances")
    for label, fn, touches in (
        ("fused BN fwd+bwd (8-touch minimum)", bn_chain, 8),
        ("pure elementwise, same 8-touch bytes", elementwise_chain, 8),
    ):
        s = _slope(fn, args)
        bw = gb * touches / s
        print(
            f"{label:42s} {s * 1e3:7.2f} ms"
            f"  ({gb * touches:5.1f} GB -> {bw:6.0f} GB/s apparent)"
        )
    print(
        "stats-pass upper bound: one touch = "
        f"{gb:.2f} GB = {gb / 819 * 1e3:.1f} ms at 819 GB/s peak"
    )


if __name__ == "__main__":
    main()
