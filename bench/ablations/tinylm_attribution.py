"""tinylm (40% MFU) component attribution — chip, bench.py windows.

The r5 VERDICT-style accounting every other bench row has: which op
classes own the non-MXU 60% of the tinylm step? Method: monkeypatch one
layer class's apply to (near-)identity before the Trainer builds, run
the standard two-window bench, and read the step-time delta — each
variant removes that class's forward AND backward. Variants:

  base       unmodified tinylm.conf (d=256, ff=1024, S=128, B=64)
  attn_id    kAttention -> identity (qkv/out projections + S^2 core gone)
  ln_id      kLayerNorm -> identity (fp32 stats + scale/bias gone)
  nogelu     kDense keeps matmul+bias, drops the activation
  cheap_loss kLMLoss -> mean(logits) (log_softmax + gather + argmax gone)

Run: python bench/ablations/tinylm_attribution.py
"""
import os
import sys

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
sys.path.insert(0, REPO)

import jax.numpy as jnp  # noqa: E402

import bench  # noqa: E402
from singa_tpu.layers import sequence as seq  # noqa: E402


def run(name):
    w = bench.bench_tinylm(name=name)
    print(f"{name:10s} {w['step_ms']*1e3:7.1f} us/step  "
          f"({w['samples_per_sec']:.0f} samples/s)")
    return w["step_ms"] * 1e3


def main():
    rows = {}
    rows["base"] = run("base")

    orig_attn = seq.AttentionLayer.apply
    seq.AttentionLayer.apply = (
        lambda self, params, inputs, *, training, rng=None: inputs[0]
    )
    rows["attn_id"] = run("attn_id")
    seq.AttentionLayer.apply = orig_attn

    orig_ln = seq.LayerNormLayer.apply
    seq.LayerNormLayer.apply = (
        lambda self, params, inputs, *, training, rng=None: inputs[0]
    )
    rows["ln_id"] = run("ln_id")
    seq.LayerNormLayer.apply = orig_ln

    orig_dense = seq.DenseLayer.apply

    def dense_noact(self, params, inputs, *, training, rng=None):
        w = params[self.w]
        out = inputs[0].astype(w.dtype) @ w
        if self.bias_term:
            out = out + params[self.b]
        return out

    seq.DenseLayer.apply = dense_noact
    rows["nogelu"] = run("nogelu")
    seq.DenseLayer.apply = orig_dense

    orig_loss = seq.LMLossLayer.apply

    def cheap_loss(self, params, inputs, *, training, rng=None):
        logits, _ = inputs
        loss = jnp.mean(logits.astype(jnp.float32))
        return loss, {"loss": loss, "precision": jnp.float32(0)}

    seq.LMLossLayer.apply = cheap_loss
    rows["cheap_loss"] = run("cheap_loss")
    seq.LMLossLayer.apply = orig_loss

    base = rows["base"]
    print("\ncomponent costs (base minus ablated):")
    for k in ("attn_id", "ln_id", "nogelu", "cheap_loss"):
        print(f"  {k:10s} {base - rows[k]:7.1f} us")


if __name__ == "__main__":
    main()
