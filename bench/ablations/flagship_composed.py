"""Flagship convergence UNDER COMPOSITION (VERDICT r4 #4).

examples/mnist/mlp.conf + its declared Elastic protocol on a
(replica=4 x model=2) mesh — the reference's actual deployment shape
(worker groups sync through the PS while kLayerPartition splits the net
inside each group, src/worker/neuralnet.cc:55-56) — for >=10k steps on
digits. The r4 convergence rows ran the protocol with an UNPARTITIONED
model; this is the composed regime.

Geometry notes: the real chip is one device, so the composed mesh runs
on the 8-virtual-device CPU host. mlp.conf's batch 1000 x 4 replicas is
~1.4 s/step there; batch 64/replica (256 records/step, ~580 ms/step
fp32) keeps the full-width layers and the conf's protocol/cadence
semantics while fitting the ~90 min budget. Accuracy bar: within noise
of the r4 Elastic row (97.5% on digits).

Run:  python bench/ablations/flagship_composed.py [steps]
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    # 4 virtual devices, not 8: XLA CPU's collective rendezvous has a
    # HARDCODED 40 s termination timeout (rendezvous.cc), and 8 device
    # threads of a full-width MLP on this 1-core host trip it
    # intermittently over a 10k-step run (two SIGABRTs observed).
    # Fewer runnable threads -> fewer missed rendezvous.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main(steps: int = 10000, workdir: str | None = None) -> dict:
    from singa_tpu.config import load_model_config
    from singa_tpu.config.schema import ClusterConfig
    from singa_tpu.data.loader import digits_arrays, write_records
    from singa_tpu.parallel import build_mesh
    from singa_tpu.trainer import ReplicaTrainer

    tmp = workdir or tempfile.mkdtemp(prefix="singa_flagship_comp_")
    os.makedirs(tmp, exist_ok=True)
    tr_sh = os.path.join(tmp, "train_shard")
    te_sh = os.path.join(tmp, "test_shard")
    # guard BOTH shards: a crash between the two writes must not leave
    # a workdir that skips the test shard forever on resume
    if not (os.path.exists(tr_sh) and os.path.exists(te_sh)):
        write_records(tr_sh, *digits_arrays("train"), append=False)
        write_records(te_sh, *digits_arrays("test"), append=False)

    cfg = load_model_config(os.path.join(REPO, "examples", "mnist", "mlp.conf"))
    for layer in cfg.neuralnet.layer:
        if layer.type == "kShardData":
            is_test = "kTrain" in (layer.exclude or [])
            layer.data_param.path = te_sh if is_test else tr_sh
            layer.data_param.batchsize = 359 if is_test else 64
    cfg.neuralnet.partition_type = "kLayerPartition"
    cfg.train_steps = steps
    cfg.test_steps = 1
    cfg.test_frequency = 0      # eval once at the end (CPU wall budget)
    cfg.display_frequency = 2000
    # checkpoint + auto-resume: XLA CPU's 40 s rendezvous abort can kill
    # a multi-hour virtual-mesh run at any window; a crash then costs at
    # most 1000 steps (this is the framework's own kill-and-resume
    # machinery doing its job — stream positions ride in the checkpoint)
    cfg.checkpoint_frequency = 1000
    cluster = ClusterConfig()
    cluster.workspace = os.path.join(tmp, "ws")
    ckdir = os.path.join(cluster.workspace, "checkpoints")
    if os.path.isdir(ckdir):
        cks = sorted(
            (f for f in os.listdir(ckdir) if f.endswith(".npz")),
            key=lambda f: int(f.split("_")[1].split(".")[0]),
        )
        if cks:
            cfg.checkpoint = os.path.join(ckdir, cks[-1])
            print(f"resuming from {cfg.checkpoint}")

    mesh = build_mesh(2, 2)
    t0 = time.time()
    tr = ReplicaTrainer(
        cfg, cluster, mesh=mesh, seed=0, log=print, prefetch=False
    )
    # the model axis is real: full-width fc weights carry a model sharding
    assert any(
        "model" in [str(a) for a in v.sharding.spec if a is not None]
        for v in tr.params.values()
    ), "composition did not engage the model axis"
    tr.run()
    wall = time.time() - t0
    final = tr.evaluate(tr.test_net, 1, "final-test", steps)
    (m,) = final.values()
    out = {
        "name": "mlp_elastic_composed",
        "mesh": dict(mesh.shape),
        "partition_type": "kLayerPartition",
        "protocol": tr.protocol,
        "steps": steps,
        "resumed_from": int(tr.start_step),
        "batch_per_replica": 64,
        "wall_sec": round(wall, 1),
        "final_test_accuracy": round(float(m["precision"]), 4),
        "final_test_loss": round(float(m["loss"]), 4),
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 10000,
        sys.argv[2] if len(sys.argv) > 2 else None,
    )
