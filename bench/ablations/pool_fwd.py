"""Max-pool FORWARD formulation A/B at AlexNet shapes (chip).

r4 attributed 92us of the AlexNet step to pools and fixed the backward
(phase-decomposed VJP, ops/nn.py); the forward stayed on
lax.reduce_window. Question: would a slice+max forward (k^2 static
strided slices reduced with jnp.maximum — the same trick the backward
uses) beat reduce_window at the AlexNet pool shapes?

MEASURED ANSWER (r5, chip, min-of-3, 200-vs-1000-iteration slope):
at (256,32,32,32) k3s2 — the largest AlexNet pool —
  reduce_window  47 us/call   (vs a 64 us harness floor: in the noise)
  slice+max     166 us/call   (3.5x WORSE: nine strided passes lose to
                               the fused window reduction)
At the two SMALLER AlexNet shapes (2.1M / 1.05M elems) the microbench
repeatedly showed slices ~5-20us cheaper — but the IN-MODEL A/B killed
it: gating a slice forward at <=3M elems into max_pool2d measured the
real cifar_alexnet bench row at 504k samples/sec vs 618k for
reduce_window, back-to-back same session (the microbench's `.sum()`
consumer fuses the slice chain in a way the conv consumer does not).
So the forward stays on reduce_window everywhere, and the r4 gate
(_PHASE_POOL_MAX_ELEMS applies the slice trick only to the BACKWARD,
where select_and_scatter is the alternative) is correct as shipped.
No code change — microbench wins must survive composition before they
ship.

Harness notes (they bit us): on the axon platform block_until_ready
does NOT force the tunnel round trip — time a float() pull. And a
`pool(x + i)` loop body gets hoisted to ~0 cost — cycle through 8
pre-materialized inputs via lax.dynamic_index_in_dim instead. Tunnel
round trips vary +-30 ms, so windows must be large (200/1000) and
each timed min-of-3.
"""
import time

import jax
import jax.numpy as jnp
from jax import lax


def pool_rw(x, k, s):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, k, k), (1, 1, s, s), "VALID"
    )


def pool_slices(x, k, s):
    b, c, h, w = x.shape
    ph = (h - k) // s + 1
    pw = (w - k) // s + 1
    need_h = (ph - 1) * s + k
    need_w = (pw - 1) * s + k
    if need_h > h or need_w > w:
        x = jnp.pad(
            x,
            ((0, 0), (0, 0), (0, max(0, need_h - h)), (0, max(0, need_w - w))),
            constant_values=-jnp.inf,
        )
    out = None
    for i in range(k):
        for j in range(k):
            sl = x[:, :, i : i + s * ph : s, j : j + s * pw : s]
            out = sl if out is None else jnp.maximum(out, sl)
    return out


def timed(fn, xs, k, s, n, reps=3):
    @jax.jit
    def many(xs):
        def body(i, acc):
            x = lax.dynamic_index_in_dim(xs, i % 8, keepdims=False)
            return acc + fn(x, k, s).sum()
        return lax.fori_loop(0, n, body, jnp.float32(0))

    float(many(xs))  # compile + settle
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        float(many(xs))  # the value pull forces the tunnel round trip
        best = min(best, time.perf_counter() - t0)
    return best


SHAPES = [  # (B,C,H,W), kernel, stride — alexnet.conf's three pools
    ((256, 32, 32, 32), 3, 2),
    ((256, 32, 16, 16), 3, 2),
    ((256, 64, 8, 8), 3, 2),
]

if __name__ == "__main__":
    for shape, k, s in SHAPES:
        xs = jax.random.normal(jax.random.PRNGKey(0), (8,) + shape,
                               jnp.bfloat16)
        rows = {}
        for name, fn in (("reduce_window", pool_rw),
                         ("slices", pool_slices),
                         ("floor", lambda x, k, s: x[:, :, ::s, ::s])):
            t1 = timed(fn, xs, k, s, 200)
            t2 = timed(fn, xs, k, s, 1000)
            rows[name] = (t2 - t1) / 800 * 1e6  # us per call, slope
        print(f"{shape} k{k}s{s}: " + "  ".join(
            f"{n} {v:.1f}us" for n, v in rows.items()))
