"""Calibrate the AlexNet convergence oracle's class amplitude.

The oracle must land in 80-95% so a regression can move it (VERDICT r4
weak #5). Two measured anchors frame the scan: the legacy independent
templates (amplitude ~160) saturate at 100%, and amplitude 6 — whose
nearest-class-mean probe reads 88.9% — trains to exactly chance (10%):
AlexNet's conf init/lr cannot extract a 2%-contrast signal the linear
probe can. The scan walks the amplitude between those regimes with the
REAL conf at full length (70k steps, the oracle's geometry).

Run (reserves the chip, ~2.5 min per point):
  python bench/ablations/alexnet_amplitude_scan.py [A ...]
"""

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_point(amplitude: float) -> dict:
    from singa_tpu.config import load_model_config
    from singa_tpu.data.loader import (
        compute_mean,
        structured_rgb,
        write_records,
    )
    from singa_tpu.tools.convergence import _patch_paths
    from singa_tpu.trainer import Trainer

    tmp = tempfile.mkdtemp(prefix="singa_ampscan_")
    train = os.path.join(tmp, "train_shard")
    test = os.path.join(tmp, "test_shard")
    write_records(
        train,
        *structured_rgb(5000, seed=0, noise_seed=1, class_amplitude=amplitude),
    )
    write_records(
        test,
        *structured_rgb(1000, seed=0, noise_seed=2, class_amplitude=amplitude),
    )
    mean = os.path.join(tmp, "mean.npy")
    compute_mean(train, mean)
    cfg = load_model_config(
        os.path.join(REPO, "examples", "cifar10", "alexnet.conf")
    )
    _patch_paths(cfg, train, test, mean)
    cfg.checkpoint_frequency = 0
    cfg.display_frequency = 0
    if not cfg.compute_dtype:
        cfg.compute_dtype = "bfloat16"
    t0 = time.perf_counter()
    tr = Trainer(cfg, seed=0, log=lambda s: None, prefetch=False)
    tr.run()
    wall = time.perf_counter() - t0
    avg = tr.evaluate(tr.test_net, cfg.test_steps, "test", cfg.train_steps)
    (m,) = avg.values()
    return {
        "amplitude": amplitude,
        "steps": cfg.train_steps,
        "wall_sec": round(wall, 1),
        "final_test_accuracy": round(float(m["precision"]), 4),
        "final_test_loss": round(float(m["loss"]), 4),
    }


def main():
    points = [float(a) for a in sys.argv[1:]] or [10.0, 16.0, 24.0]
    for a in points:
        print(json.dumps(run_point(a)), flush=True)


if __name__ == "__main__":
    main()
